"""ScenarioRunner: drive a SoakFleet through a scenario spec.

One run = phases in order.  Per phase the runner

- replays the phase's traffic plan (open-loop Poisson arrivals and/or
  closed-loop multi-turn sessions) against the fleet's dispatcher, with
  frontend-style pre-first-token retries;
- arms ``DYN_FAULTS`` schedules at their phase-relative times (chaos
  mid-phase, exactly where production faults land);
- feeds every TTFT/ITL/error outcome into the SloTracker on the SIMULATED
  clock, and samples ``/slo`` + the metrics service each tick via
  ``scripts/dyn_top.collect_snapshot`` (the artifact's time series);
- steps the planner autopilot on its own cadence — burn rates and per-pool
  utilization in, replica decisions out, executed live through
  ``LocalConnector`` → ``SoakFleet.set_replicas`` while traffic flows;
- evaluates the phase's assertions on PHASE-LOCAL counts when it drains.

``run()`` returns the artifact dict (SCENARIO_SOAK.json): per-phase
TTFT/ITL percentiles, burn rates, MFU/goodput, injected faults, planner
decision log, dyn_top snapshots, and a pass/fail verdict per assertion.
"""

from __future__ import annotations

import asyncio
import random
import sys
import time
from pathlib import Path

from dynamo_tpu.llm.protocols.common import (
    Annotated,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.observability import flight as flight_obs
from dynamo_tpu.observability.slo import SloConfig, SloObjective, SloTracker
from dynamo_tpu.planner import (
    DefragConfig,
    Defragmenter,
    PerfProfile,
    Planner,
    PlannerConfig,
    PlannerStatePublisher,
    ProfilePoint,
    sample_from_endpoints,
)
from dynamo_tpu.planner.connectors import LocalConnector
from dynamo_tpu.robustness import counters
from dynamo_tpu.robustness.faults import FAULTS
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.scenarios.fleet import SoakFleet
from dynamo_tpu.scenarios.spec import Phase, ScenarioSpec
from dynamo_tpu.scenarios.traffic import PhasePlan, plan_phase, prompt_tokens
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("scenarios.runner")

# scripts/ is not a package; import dyn_top the way the tests do
_SCRIPTS = str(Path(__file__).resolve().parents[2] / "scripts")
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)
from dyn_top import collect_snapshot  # noqa: E402


def _pctile(xs: list[float], q: float) -> float | None:
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * q))] if xs else None


def _slo_config(spec: ScenarioSpec) -> SloConfig:
    s = spec.slo
    return SloConfig(
        objectives=(
            SloObjective("ttft", s.ttft_target, threshold_s=s.ttft_s),
            SloObjective("itl", s.itl_target, threshold_s=s.itl_s),
            SloObjective("error_rate", s.error_target),
        ),
        windows_s=tuple(float(w) for w in s.windows_s),
        shed_burn_threshold=s.shed_burn,
    )


def _bootstrap_profile(spec: ScenarioSpec) -> PerfProfile:
    p = spec.autopilot.profile
    mk = lambda isl, osl: ProfilePoint(  # noqa: E731
        isl=isl, osl=osl,
        prefill_tok_s=float(p.get("prefill_tok_s", 50_000.0)),
        decode_tok_s=float(p.get("decode_tok_s", 5_000.0)),
        ttft_s=float(p.get("ttft_s", 0.02)),
        itl_s=float(p.get("itl_s", 0.01)),
    )
    return PerfProfile([mk(16, 8), mk(8192, 1024)])


class _PhaseStats:
    """Phase-local observation store (assertions are evaluated on these, so
    one phase's damage cannot fail its neighbor)."""

    def __init__(self) -> None:
        self.ttfts: list[float] = []
        self.itls: list[float] = []
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.retries = 0
        self.abandoned = 0
        self.by_kind: dict[str, int] = {}
        # verify_outputs bookkeeping: completed requests whose streamed
        # tokens matched / diverged from the deterministic greedy reference
        self.verified = 0
        self.corrupt = 0

    def burn(self, spec: ScenarioSpec) -> dict[str, float]:
        s = spec.slo

        def _rate(bad: int, total: int, target: float) -> float:
            if not total:
                return 0.0
            return (bad / total) / max(1.0 - target, 1e-9)

        ttft_bad = sum(1 for t in self.ttfts if t > s.ttft_s)
        itl_bad = sum(1 for t in self.itls if t > s.itl_s)
        finished = self.completed + self.failed
        return {
            "ttft": _rate(ttft_bad, len(self.ttfts), s.ttft_target),
            "itl": _rate(itl_bad, len(self.itls), s.itl_target),
            "error_rate": _rate(self.failed, finished, s.error_target),
        }


class ScenarioRunner:
    def __init__(self, spec: ScenarioSpec, *, name: str | None = None):
        self.spec = spec.validate()
        self.fleet: SoakFleet | None = None
        self.slo = SloTracker(_slo_config(spec))
        self.planner: Planner | None = None
        self.state_pub: PlannerStatePublisher | None = None
        self._t0_wall = 0.0
        self.decisions: list[dict] = []
        self.ticks: list[dict] = []
        self.top_snapshots: list[dict] = []
        self._name = name or f"{spec.name}-{spec.seed}"
        # autopilot sampling window state
        self._window_submitted = 0
        self._window_isl: list[int] = []
        self._window_osl: list[int] = []
        self._window_ttfts: list[float] = []
        self._window_itls: list[float] = []
        self._next_plan_t = 0.0
        # planner-driven defragmentation (autopilot.defrag)
        self.defrag: Defragmenter | None = None
        self._next_defrag_t = 0.0

    # -- simulated clock -----------------------------------------------------
    def sim_now(self) -> float:
        return (time.monotonic() - self._t0_wall) * self.spec.speedup

    async def _sim_sleep_until(self, sim_t: float) -> None:
        delay = (sim_t - self.sim_now()) / self.spec.speedup
        if delay > 0:
            await asyncio.sleep(delay)

    # -- request execution ---------------------------------------------------
    async def _execute(self, stats: _PhaseStats, tokens: list[int], osl: int,
                       kind: str, history: list[int] | None = None) -> bool:
        """Send one request; returns success.  Pre-first-token failures are
        retried (the frontend's retry role — KV-affine dispatch is direct,
        so PushRouter's own retry is bypassed and the caller must re-issue).
        ``history`` (session mode) collects the streamed tokens."""
        spec = self.spec
        stats.submitted += 1
        stats.by_kind[kind] = stats.by_kind.get(kind, 0) + 1
        self._window_submitted += 1
        self._window_isl.append(len(tokens))
        self._window_osl.append(osl)
        wire = PreprocessedRequest(
            token_ids=list(tokens),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
            eos_token_ids=[],
        ).to_wire()
        for attempt in range(spec.retry_max + 1):
            t0 = self.sim_now()
            ttft = None
            last_emit = None
            got: list[int] = []
            try:
                stream = await self.fleet.dispatcher.generate(Context(dict(wire)))
                async for item in stream:
                    ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
                    if ann.data is None or not ann.data.token_ids:
                        continue
                    now = self.sim_now()
                    if ttft is None:
                        ttft = now - t0
                        stats.ttfts.append(ttft)
                        self._window_ttfts.append(ttft)
                        self.slo.observe_latency("ttft", ttft, now=now)
                    elif last_emit is not None:
                        itl = now - last_emit
                        stats.itls.append(itl)
                        self._window_itls.append(itl)
                        self.slo.observe_latency("itl", itl, now=now)
                    last_emit = now
                    got.extend(ann.data.token_ids)
                    if history is not None:
                        history.extend(ann.data.token_ids)
                stats.completed += 1
                if spec.verify_outputs:
                    if spec.fleet.engine == "mocker":
                        # the mocker's greedy chain is fully determined by
                        # the prompt's last token — so the reference an
                        # unmigrated run would stream is computable without
                        # running it, and any resume/migration replay or
                        # drop shows up here
                        last = tokens[-1] if tokens else -1
                        expected = [(last + 1 + i) % 1000 for i in range(osl)]
                    else:
                        # real engines sample real logits: the strongest
                        # engine-agnostic invariant is the token COUNT the
                        # stop conditions demand (ignore_eos + max_tokens)
                        expected = None
                    if (got == expected if expected is not None
                            else len(got) == osl):
                        stats.verified += 1
                    else:
                        stats.corrupt += 1
                        logger.warning(
                            "output diverged from greedy reference "
                            "(kind=%s len=%d want=%d)", kind, len(got), osl,
                        )
                self.slo.observe_outcome("error_rate", True, now=self.sim_now())
                return True
            except asyncio.CancelledError:
                stats.abandoned += 1
                raise
            except Exception as exc:  # noqa: BLE001 — chaos faults land here
                if ttft is None and attempt < spec.retry_max:
                    stats.retries += 1
                    counters.incr("dyn_retries_total")
                    continue
                logger.debug("request failed (%s attempts): %s", attempt + 1, exc)
                stats.failed += 1
                self.slo.observe_outcome("error_rate", False, now=self.sim_now())
                return False
        return False

    async def _run_arrival(self, stats: _PhaseStats, phase_t0: float,
                           arrival, rng: random.Random) -> None:
        await self._sim_sleep_until(phase_t0 + arrival.at_s)
        await self._execute(
            stats, prompt_tokens(arrival.isl, rng), arrival.osl, arrival.kind
        )

    async def _run_session(self, stats: _PhaseStats, phase_t0: float,
                           sess) -> None:
        """Closed-loop multi-turn session: each turn's prompt embeds the
        actual streamed history (chat clients echo assistant tokens)."""
        await self._sim_sleep_until(phase_t0 + sess.start_s)
        history = list(sess.system_tokens)
        for i, turn in enumerate(sess.turns):
            if i and turn.arrival_gap_s:
                await asyncio.sleep(turn.arrival_gap_s / self.spec.speedup)
            history.extend(turn.user_tokens)
            await self._execute(stats, history, turn.osl, "session",
                                history=history)

    # -- chaos ---------------------------------------------------------------
    async def _arm_later(self, phase: Phase, ev, phase_t0: float,
                         armed: list) -> None:
        await self._sim_sleep_until(phase_t0 + ev.at_s)
        FAULTS.arm(ev.schedule)
        armed.append({"t": round(self.sim_now(), 3), "schedule": ev.schedule})
        logger.info("phase %s: armed faults %r", phase.name, ev.schedule)

    async def _kill_later(self, phase: Phase, ev, phase_t0: float,
                          killed: list) -> None:
        await self._sim_sleep_until(phase_t0 + ev.at_s)
        wid = await self.fleet.kill_worker(ev.pool, mode=ev.mode)
        killed.append({
            "t": round(self.sim_now(), 3), "pool": ev.pool, "mode": ev.mode,
            "worker": None if wid is None else f"{wid:x}",
        })
        logger.info("phase %s: %s worker %s in pool %s",
                    phase.name, ev.mode, wid, ev.pool)

    async def _migrate_later(self, phase: Phase, ev, phase_t0: float,
                             migrated: list) -> None:
        """MigrationEvent: live-migrate up to ``count`` in-flight sessions,
        each to the coordinator's cheapest-hop pick.  Refusals (session
        finished between listing and migrating, no destination) are recorded
        and skipped — the event keeps walking the registry until it commits
        ``count`` moves or runs out of sessions."""
        await self._sim_sleep_until(phase_t0 + ev.at_s)
        coord = getattr(self.fleet.push, "migrations", None)
        if coord is None:
            migrated.append({"t": round(self.sim_now(), 3),
                             "error": "migration disabled (DYN_MIGRATE=0)"})
            return
        committed = 0
        for rid in sorted(coord.sessions()):
            if committed >= ev.count:
                break
            res = await coord.migrate(rid, None, reason=ev.reason)
            migrated.append({
                "t": round(self.sim_now(), 3), "request": rid,
                "ok": bool(res.get("ok")), "src": res.get("src"),
                "dst": res.get("dst"), "hop": res.get("hop"),
                "error": res.get("error"),
            })
            if res.get("ok"):
                committed += 1
        logger.info("phase %s: migration event committed %d/%d",
                    phase.name, committed, ev.count)

    # -- defrag ---------------------------------------------------------------
    def _occupancy(self) -> dict[int, float]:
        """Per-worker KV occupancy from the live metrics aggregator."""
        snap = self.fleet.metrics_service.aggregator.snapshot()
        return {
            wid: float(getattr(m, "gpu_cache_usage_perc", 0.0))
            for wid, m in snap.workers.items()
        }

    # -- autopilot -----------------------------------------------------------
    async def _autopilot_step(self, phase_name: str) -> None:
        ap = self.spec.autopilot
        interval = max(ap.interval_s, 1e-6)
        now = self.sim_now()
        # request_rate in WALL req/s (sim rate × speedup) so demand matches
        # the mocker's wall-clock goodput capacity units
        rate_sim = self._window_submitted / interval
        mean = lambda xs, d: (sum(xs) / len(xs)) if xs else d  # noqa: E731
        sample = sample_from_endpoints(
            self.fleet.metrics_service.aggregator.snapshot(),
            request_rate=rate_sim * self.spec.speedup,
            avg_isl=mean(self._window_isl, 64.0),
            avg_osl=mean(self._window_osl, 16.0),
            ttft_s=mean(self._window_ttfts, 0.0),
            itl_s=mean(self._window_itls, 0.0),
            roles=self.fleet.roles(),
            slo_status=self.slo.status(now),
        )
        self._window_submitted = 0
        self._window_isl.clear()
        self._window_osl.clear()
        self._window_ttfts.clear()
        self._window_itls.clear()
        decision = await self.planner.step(sample, now=now)
        self.decisions.append({
            "t": round(now, 3),
            "phase": phase_name,
            "reason": decision.reason,
            "num_prefill": decision.num_prefill,
            "num_decode": decision.num_decode,
            "burn_input": round(self.planner.worst_burn_input, 4),
            "request_rate_sim": round(rate_sim, 3),
            "executed": {
                pool: self.fleet.replica_count(pool)
                for pool in self.spec.fleet.pools
            },
        })

    # -- ticks ---------------------------------------------------------------
    def _capture_top(self) -> dict:
        return collect_snapshot(
            frontend=self.fleet.frontend_url,
            worker=self.fleet.worker_url,
            timeout=3.0,
        )

    async def _tick(self, phase_name: str) -> None:
        snap = await asyncio.to_thread(self._capture_top)
        fleet = snap.get("fleet") or {}
        now = self.sim_now()
        # cross-worker KV-occupancy dispersion: the defrag loop's input and
        # the migration bench's before/after measurement
        occ = self._occupancy()
        mean_occ = sum(occ.values()) / len(occ) if occ else 0.0
        var = (
            sum((v - mean_occ) ** 2 for v in occ.values()) / len(occ)
            if occ else 0.0
        )
        self.ticks.append({
            "t": round(now, 3),
            "phase": phase_name,
            "workers": fleet.get("workers", 0),
            "goodput_tok_s": round(fleet.get("goodput_tokens_per_second", 0.0), 2),
            "mfu": round(fleet.get("mfu_perc_avg", 0.0), 4),
            "waiting": fleet.get("waiting", 0),
            "running": fleet.get("running", 0),
            "worst_burn": round(self.slo.worst_burn_rate(now), 3),
            "kv_occ_mean": round(mean_occ, 4),
            "kv_occ_var": round(var, 6),
            "kv_occ_spread": round(Defragmenter.spread(occ), 4),
            "planner": snap.get("planner"),
        })

    # -- phase ---------------------------------------------------------------
    async def _run_phase(self, phase: Phase) -> dict:
        spec = self.spec
        plan: PhasePlan = plan_phase(phase, spec.seed)
        stats = _PhaseStats()
        rng = random.Random((spec.seed, phase.name, "prompts").__repr__())
        phase_t0 = self.sim_now()
        faults_before = counters.get("dyn_faults_injected_total")
        armed: list = []
        ticks_before = len(self.ticks)
        selections_before = dict(self.fleet.selection_counts)

        work = [
            asyncio.ensure_future(self._run_arrival(stats, phase_t0, a, rng))
            for a in plan.arrivals
        ] + [
            asyncio.ensure_future(self._run_session(stats, phase_t0, s))
            for s in plan.sessions
        ]
        killed: list = []
        migrated: list = []
        mig_before = {
            k: counters.get(f"dyn_migration_{k}_total")
            for k in ("started", "committed", "aborted", "failed")
        }
        chaos = [
            asyncio.ensure_future(self._arm_later(phase, ev, phase_t0, armed))
            for ev in phase.faults
        ] + [
            asyncio.ensure_future(self._kill_later(phase, ev, phase_t0, killed))
            for ev in phase.worker_kills
        ] + [
            asyncio.ensure_future(
                self._migrate_later(phase, ev, phase_t0, migrated)
            )
            for ev in phase.migrations
        ]

        # tick/autopilot loop for the phase duration
        mid_captured = False
        while self.sim_now() - phase_t0 < phase.duration_s:
            await asyncio.sleep(spec.tick_s / spec.speedup)
            await self._tick(phase.name)
            now = self.sim_now()
            if spec.autopilot.enabled and now >= self._next_plan_t:
                self._next_plan_t = now + spec.autopilot.interval_s
                await self._autopilot_step(phase.name)
            if self.defrag is not None and now >= self._next_defrag_t:
                self._next_defrag_t = now + spec.autopilot.interval_s
                await self.defrag.step(self._occupancy(), now=now)
            if not mid_captured and now - phase_t0 >= phase.duration_s / 2:
                mid_captured = True
                snap = await asyncio.to_thread(self._capture_top)
                snap["phase"] = phase.name
                self.top_snapshots.append(snap)

        # drain: give in-flight requests a bounded grace window
        if work:
            done, pending = await asyncio.wait(
                work, timeout=spec.drain_s / spec.speedup
            )
            for task in pending:
                task.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
        for task in chaos:
            task.cancel()
        await asyncio.gather(*chaos, return_exceptions=True)

        burn = stats.burn(spec)
        phase_ticks = self.ticks[ticks_before:]
        mean_tick = lambda key: (  # noqa: E731
            sum(t[key] for t in phase_ticks) / len(phase_ticks)
            if phase_ticks else 0.0
        )
        goodput = mean_tick("goodput_tok_s")
        mfu = mean_tick("mfu")

        failures: list[str] = []
        a = phase.assertions
        for objective, ceiling in (a.max_burn_rate or {}).items():
            got = burn.get(objective)
            if got is None:
                failures.append(f"unknown objective in max_burn_rate: {objective}")
            elif got > ceiling:
                failures.append(
                    f"burn[{objective}]={got:.2f} exceeds ceiling {ceiling}"
                )
        if a.min_goodput_tok_s and goodput < a.min_goodput_tok_s:
            failures.append(
                f"goodput {goodput:.1f} tok/s below floor {a.min_goodput_tok_s}"
            )
        if a.min_mfu and mfu < a.min_mfu:
            failures.append(f"mfu {mfu:.3f} below floor {a.min_mfu}")
        if a.min_completed and stats.completed < a.min_completed:
            failures.append(
                f"completed {stats.completed} below floor {a.min_completed}"
            )
        mig_counts = {
            k: counters.get(f"dyn_migration_{k}_total") - v
            for k, v in mig_before.items()
        }
        if (
            a.min_migrations_committed
            and mig_counts["committed"] < a.min_migrations_committed
        ):
            failures.append(
                f"migrations committed {mig_counts['committed']} below floor "
                f"{a.min_migrations_committed}"
            )
        if a.max_failed >= 0 and stats.failed > a.max_failed:
            failures.append(
                f"failed requests {stats.failed} exceed ceiling {a.max_failed}"
            )
        if spec.verify_outputs and stats.corrupt:
            failures.append(
                f"{stats.corrupt} completed request(s) streamed tokens "
                "diverging from the greedy reference"
            )

        # topology-aware routing: where did this phase's selections land?
        topology_view = None
        if spec.fleet.slices:
            by_slice: dict[str, int] = {}
            for wid, count in self.fleet.selection_counts.items():
                delta = count - selections_before.get(wid, 0)
                if delta > 0:
                    label = self.fleet.slice_of(wid) or "-"
                    by_slice[label] = by_slice.get(label, 0) + delta
            total_sel = sum(by_slice.values())
            near = by_slice.get(self.fleet.near_slice, 0)
            near_fraction = near / total_sel if total_sel else 0.0
            topology_view = {
                "near_slice": self.fleet.near_slice,
                "selections_by_slice": by_slice,
                "near_fraction": round(near_fraction, 4),
            }
            if a.min_near_slice_fraction:
                if not total_sel:
                    failures.append(
                        "min_near_slice_fraction set but no routed selections "
                        "observed (policy must be kv)"
                    )
                elif near_fraction < a.min_near_slice_fraction:
                    failures.append(
                        f"near-slice fraction {near_fraction:.2f} below floor "
                        f"{a.min_near_slice_fraction} ({by_slice})"
                    )
        elif a.min_near_slice_fraction:
            failures.append(
                "min_near_slice_fraction set but fleet.slices is empty"
            )

        ms = lambda x: None if x is None else round(x * 1000.0, 2)  # noqa: E731
        return {
            "name": phase.name,
            "traffic": phase.traffic.kind,
            "duration_s": phase.duration_s,
            "requests": {
                "planned": plan.expected_requests,
                "submitted": stats.submitted,
                "completed": stats.completed,
                "failed": stats.failed,
                "retries": stats.retries,
                "abandoned_in_drain": stats.abandoned,
                "by_kind": stats.by_kind,
            },
            # simulated milliseconds (speedup-independent)
            "ttft_sim_ms": {
                "p50": ms(_pctile(stats.ttfts, 0.5)),
                "p90": ms(_pctile(stats.ttfts, 0.9)),
                "p99": ms(_pctile(stats.ttfts, 0.99)),
            },
            "itl_sim_ms": {
                "p50": ms(_pctile(stats.itls, 0.5)),
                "p90": ms(_pctile(stats.itls, 0.9)),
                "p99": ms(_pctile(stats.itls, 0.99)),
            },
            "burn_rates": {k: round(v, 3) for k, v in burn.items()},
            "goodput_tok_s_mean": round(goodput, 2),
            "mfu_mean": round(mfu, 4),
            "faults": {
                "armed": armed,
                "injected": counters.get("dyn_faults_injected_total") - faults_before,
                "fired": dict(FAULTS.fired),
            },
            "worker_kills": killed,
            "migrations": {"events": migrated, **mig_counts},
            "outputs": (
                {"verified": stats.verified, "corrupt": stats.corrupt}
                if spec.verify_outputs else None
            ),
            "topology": topology_view,
            "resumes": {
                "attempts": counters.get("dyn_resume_attempts_total"),
                "succeeded": counters.get("dyn_resume_success_total"),
            },
            "assertions": {"passed": not failures, "failures": failures},
        }

    # -- the run -------------------------------------------------------------
    async def run(self) -> dict:
        spec = self.spec
        FAULTS.reset()
        flight_dumps: list[str] = []
        wall_start = time.monotonic()
        self._t0_wall = wall_start
        self.fleet = SoakFleet(
            spec=spec, slo=self.slo, sim_now=self.sim_now, name=self._name
        )
        phases: list[dict] = []
        try:
            await self.fleet.start()
            if spec.autopilot.enabled:
                ap = spec.autopilot
                connector = LocalConnector(
                    self.fleet, prefill_watcher="prefill", decode_watcher="decode"
                )
                self.planner = Planner(
                    _bootstrap_profile(spec), connector,
                    PlannerConfig(
                        adjustment_interval_s=ap.interval_s,
                        predictor="ewma",
                        min_prefill=ap.min_prefill, max_prefill=ap.max_prefill,
                        min_decode=ap.min_decode, max_decode=ap.max_decode,
                        max_total_chips=ap.max_total_chips,
                        burn_upscale=ap.burn_upscale,
                        burn_hold=ap.burn_hold,
                        cooldown_s=ap.cooldown_s,
                        rebalance=ap.rebalance,
                        rebalance_occupancy=ap.rebalance_occupancy,
                        saturation_occupancy=ap.saturation_occupancy,
                        scale_down_headroom=ap.scale_down_headroom,
                    ),
                    clock=self.sim_now,
                )
                self.state_pub = PlannerStatePublisher(
                    self.fleet.comp, clock=self.sim_now
                )
                self.planner.state_publisher = self.state_pub
            if spec.autopilot.defrag:
                coord = getattr(self.fleet.push, "migrations", None)
                if coord is None:
                    logger.warning(
                        "autopilot.defrag set but live migration is disabled "
                        "(DYN_MIGRATE=0); defrag loop stays off"
                    )
                else:
                    ap = spec.autopilot
                    self.defrag = Defragmenter(
                        coord,
                        DefragConfig(
                            enabled=True,
                            occupancy_spread=ap.defrag_spread,
                            min_occupancy=ap.defrag_min_occupancy,
                            max_per_step=ap.defrag_max_per_step,
                            cooldown_s=ap.defrag_cooldown_s,
                        ),
                        clock=self.sim_now,
                    )

            # re-zero the simulated clock: fleet bring-up wall time must not
            # eat into phase 1's simulated window
            self._t0_wall = time.monotonic()
            self._next_plan_t = spec.autopilot.interval_s
            self._next_defrag_t = spec.autopilot.interval_s

            for phase in spec.phases:
                logger.info("phase %s starting at sim t=%.1fs",
                            phase.name, self.sim_now())
                phases.append(await self._run_phase(phase))
            # close the observability loop before teardown: every live
            # engine's flight ring becomes a JSONL artifact the planner's
            # replay_trace() can fit predictors from
            flight_dumps = [str(p) for p in flight_obs.dump_all("soak_end")]
        finally:
            FAULTS.reset()
            if self.fleet is not None:
                await self.fleet.stop()

        steered = [d for d in self.decisions if d["reason"] != "load"]
        passed = all(p["assertions"]["passed"] for p in phases)
        if spec.autopilot.expect_decision and not steered:
            passed = False
        return {
            "scenario": spec.name,
            "seed": spec.seed,
            "speedup": spec.speedup,
            "policy": spec.fleet.policy,
            "pools": dict(spec.fleet.pools),
            "topology": (
                None if self.fleet.topo_watch is None
                else self.fleet.topo_watch.map.to_dict()
            ),
            "wall_s": round(time.monotonic() - wall_start, 2),
            "sim_s": round(self.sim_now(), 2),
            "phases": phases,
            "planner": {
                "enabled": spec.autopilot.enabled,
                "decisions": self.decisions,
                "steering_decisions": len(steered),
                "scale_events": list(self.fleet.scale_log),
            },
            "migrations": {
                "committed": counters.get("dyn_migration_committed_total"),
                "aborted": counters.get("dyn_migration_aborted_total"),
                "failed": counters.get("dyn_migration_failed_total"),
                "defrag_moves": (
                    [] if self.defrag is None else list(self.defrag.moves)
                ),
            },
            "slo": self.slo.status(self.sim_now()),
            "flight": {
                "enabled": flight_obs.flight_enabled(),
                "dumps": flight_dumps,
            },
            "ticks": self.ticks,
            "dyn_top_snapshots": self.top_snapshots,
            "passed": passed,
        }


async def run_scenario(spec: ScenarioSpec, *, name: str | None = None) -> dict:
    return await ScenarioRunner(spec, name=name).run()
