"""The native JAX inference engine.

Replaces the reference's external engines (vLLM/SGLang/TRT-LLM adapters,
SURVEY.md §2.3) with an in-process TPU engine: paged KV cache in HBM,
continuous-batching scheduler, jitted prefill/decode steps with SPMD
sharding, per-token async streaming, and KV/load event publishing for the
KV-aware router.
"""

from dynamo_tpu.engine.engine import EngineConfig, JaxLlmEngine

__all__ = ["EngineConfig", "JaxLlmEngine"]
