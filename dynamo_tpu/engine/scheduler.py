"""Continuous-batching scheduler.

Policy (same family as the reference's mocker scheduler — watermark + budget
with preemption, lib/llm/src/mocker/scheduler.rs:16-205 — and vLLM's):

- admit waiting prefills FCFS while KV blocks (plus watermark) allow and a
  decode lane is free;
- every step, decode all running lanes in one batched call;
- if a running sequence can't grow (no free block), preempt the youngest
  running sequence (free its blocks, recompute later).

The scheduler is host-side bookkeeping only — device work happens in the
engine's jitted step functions.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from dynamo_tpu.engine.kv_manager import BlockAllocator
from dynamo_tpu.engine.sequence import Sequence, SeqStatus
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("engine.scheduler")


@dataclass
class ScheduleDecision:
    prefills: list[Sequence]
    decodes: list[Sequence]
    preempted: list[Sequence]


class Scheduler:
    def __init__(
        self,
        allocator: BlockAllocator,
        *,
        max_batch_size: int,
        max_prefills_per_step: int = 1,
        prefill_chunk_tokens: int | None = None,
        bucket_cost=None,
        unified_batch: bool = False,
    ):
        self.allocator = allocator
        self.max_batch_size = max_batch_size
        self.max_prefills_per_step = max_prefills_per_step
        # chunked prefill: prompts longer than this prefill in chunks
        # interleaved with decode steps (None = whole-prompt prefill)
        self.prefill_chunk_tokens = prefill_chunk_tokens
        # unified-batch mode: decode tokens and chunked-prefill tokens ride
        # ONE ragged window, so the per-step token budget must charge the
        # decode lanes already in it before planning chunks (split mode
        # keeps the historical prefill-only budget — decode runs as its own
        # dispatch there, and its cost is not fungible with chunk tokens)
        self.unified_batch = unified_batch
        # budget accounting charges the PADDED compute of a window (the
        # engine's compile-bucket length), not raw tokens — otherwise a
        # split budget multiplies real per-step prefill work
        self.bucket_cost = bucket_cost or (lambda t: t)
        self.waiting: deque[Sequence] = deque()
        self.running: list[Sequence] = []
        self._free_lanes = list(range(max_batch_size - 1, -1, -1))
        # step telemetry: cumulative preemption count (KV-pressure evidence
        # exported as dyn_worker_preemptions via the metrics service)
        self.preemptions_total = 0
        # wasted-work accounting: every preempted sequence recomputes its
        # whole context, so those tokens were computed for nothing
        self.preempted_tokens_total = 0
        # optional hook fired on every preemption (the engine closes the
        # victim's tracing spans here; the scheduler itself stays
        # observability-agnostic)
        self.on_preempt = None

    # -- queue ops ---------------------------------------------------------
    def add(self, seq: Sequence) -> None:
        self.waiting.append(seq)

    def abort(self, seq: Sequence) -> None:
        if seq in self.running:
            self._release(seq)
        elif seq in self.waiting:
            self.waiting.remove(seq)

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- core policy -------------------------------------------------------
    def schedule(self) -> ScheduleDecision:
        preempted: list[Sequence] = []

        # 1) grow running sequences; preempt youngest on OOM
        survivors: list[Sequence] = []
        for seq in sorted(self.running, key=lambda s: s.arrival_time):
            survivors.append(seq)
        self.running = survivors
        # (growth happens in the engine when it asks for append slots; the
        # preemption hook is exposed via ensure_slot below)

        # 2) continue in-flight chunked prefills, oldest first, under a
        # SHARED per-step token budget (prefill_chunk_tokens): total prefill
        # work per iteration is bounded regardless of how many prefills are
        # in flight, so decode ITL stays bounded (vLLM-style budget)
        bs = self.allocator.block_size
        budget = self.prefill_chunk_tokens  # None = unlimited
        if budget is not None and self.unified_batch:
            # one decode token per running lane shares this step's window:
            # draw them from the same budget so a decode-saturated window
            # shrinks (or skips) its chunk share instead of overrunning
            n_decode = sum(
                1 for s in self.running if s.status == SeqStatus.RUNNING
            )
            budget = max(0, budget - n_decode)
        prefills: list[Sequence] = []
        continuing = sorted(
            (s for s in self.running if s.status == SeqStatus.PREFILLING),
            key=lambda s: s.arrival_time,
        )
        for seq in continuing:
            if budget is not None and budget < bs:
                break
            cost = self._plan_chunk(seq, seq.prefilled_tokens, budget)
            if cost is None:
                break
            if budget is not None:
                budget -= cost
            prefills.append(seq)

        # 3) admit new prefills with the leftover budget while blocks +
        # lanes allow
        admitted = 0
        while (
            self.waiting
            and admitted < self.max_prefills_per_step
            and len(self.running) < self.max_batch_size
            and self._free_lanes
            # enough budget for the smallest possible padded window — this
            # is what makes the post-allocation plan assert hold
            and (budget is None or budget >= self._chunk_cost(bs))
        ):
            candidate = self.waiting[0]
            if candidate.remote_prefilled:
                # KV was injected by a prefill worker into blocks this engine
                # reserved earlier (already adopted): no local prefill compute
                self.waiting.popleft()
                candidate.status = SeqStatus.RUNNING
                candidate.lane = self._free_lanes.pop()
                self.running.append(candidate)
                continue
            # context_len covers preempted sequences re-prefilling with their
            # generated tokens appended; +1 reserves the first decode slot
            if not self.allocator.can_allocate(candidate.context_len + 1):
                break
            self.waiting.popleft()
            # multimodal prompts: block hashes cover text tokens only, so
            # they neither match nor publish into the prefix registry, and
            # they prefill whole (embeds don't chunk)
            mm = candidate.mm_embeds is not None
            alloc = self.allocator.allocate_sequence(
                candidate.seq_id, candidate.context_len + 1,
                token_ids=None if mm else candidate.all_token_ids,
            )
            assert alloc is not None
            _, candidate.cached_tokens = alloc
            candidate.prefilled_tokens = candidate.cached_tokens
            if mm:
                candidate.chunk_target = candidate.context_len
            else:
                cost = self._plan_chunk(candidate, candidate.cached_tokens, budget)
                assert cost is not None  # budget >= bs guarantees a plan
                if budget is not None:
                    budget -= cost
            candidate.status = (
                SeqStatus.PREFILLING
                if candidate.chunk_target < candidate.context_len
                else SeqStatus.RUNNING
            )
            candidate.lane = self._free_lanes.pop()
            prefills.append(candidate)
            self.running.append(candidate)
            admitted += 1

        decodes = [s for s in self.running if s not in prefills]
        return ScheduleDecision(prefills=prefills, decodes=decodes, preempted=preempted)

    def _chunk_cost(self, take: int) -> int:
        """Budget cost of a ``take``-token chunk window.  Split mode charges
        the PADDED compute (each chunk runs as its own bucketed dispatch);
        unified mode charges raw tokens — decode lanes and every chunk share
        ONE window whose single bucket the engine picks, so padding the
        per-chunk cost there would double-count (and a post-decode-charge
        budget could never afford a full bucket, starving admission)."""
        return take if self.unified_batch else self.bucket_cost(take)

    def _plan_chunk(self, seq: Sequence, start: int, budget: int | None) -> int | None:
        """Set ``seq.chunk_target`` for this step's prefill window starting
        at ``start``; intermediate chunk ends stay block-aligned and the
        window's compute (_chunk_cost) must fit ``budget``.  Returns the
        budget cost charged, or None when nothing affordable fits."""
        remaining = seq.context_len - start
        if budget is None:
            seq.chunk_target = seq.context_len
            return 0
        bs = self.allocator.block_size
        take = min(remaining, budget)
        if take < remaining:  # intermediate end must be block-aligned
            take = (take // bs) * bs
        # shrink until the window's charged compute fits the budget
        while take > 0 and self._chunk_cost(take) > budget:
            take = ((take - 1) // bs) * bs
        if take <= 0:
            return None
        seq.chunk_target = start + take
        return self._chunk_cost(take)

    def ensure_slot(self, seq: Sequence) -> int | None:
        """Get the cache slot for this sequence's next token, preempting the
        youngest other running sequence if the pool is exhausted."""
        return self.ensure_slots(seq, 1)

    def ensure_slots(self, seq: Sequence, steps: int, max_pos: int | None = None) -> int | None:
        """Like ensure_slot but pre-extends the block table to cover a
        ``steps``-token decode window (positions capped at ``max_pos``)."""
        while True:
            slot = self.allocator.append_slots(seq.seq_id, seq.context_len, steps, max_pos)
            if slot is not None:
                return slot
            victim = self._youngest_other(seq)
            if victim is None:
                return None  # nothing to preempt; caller must handle
            self.preempt(victim)

    def try_slots_at(
        self, seq: Sequence, context_len: int, steps: int,
        max_pos: int | None = None,
    ) -> int | None:
        """``ensure_slots`` at an EXPLICIT context length (the overlapped
        decode pipeline allocates at the device-side context —
        ``seq.context_len + seq.inflight_tokens`` — because in-flight
        windows have already advanced past what the host retired), and
        WITHOUT preemption: while a window is in flight, freeing a victim's
        blocks would let the lagged device step garbage-write into storage
        the allocator may re-issue or prefix-match.  On None the engine
        drains the pipeline and retries through the preempting sync path."""
        return self.allocator.append_slots(seq.seq_id, context_len, steps, max_pos)

    def _youngest_other(self, seq: Sequence) -> Sequence | None:
        candidates = [s for s in self.running if s is not seq]
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.arrival_time)

    def preempt(self, seq: Sequence) -> None:
        logger.warning("preempting sequence %s (recompute)", seq.seq_id)
        self.preemptions_total += 1
        self.preempted_tokens_total += max(seq.context_len, 0)
        if self.on_preempt is not None:
            self.on_preempt(seq)
        self._release(seq)
        seq.status = SeqStatus.PREEMPTED
        # remotely-prefilled KV is gone once blocks are freed: recompute locally
        seq.remote_prefilled = False
        seq.prefilled_tokens = 0
        # preemption only ever happens with the decode pipeline drained
        # (try_slots_at never preempts); zero the in-flight count anyway so
        # the recompute path starts from clean accounting
        seq.inflight_tokens = 0
        # re-queue at the front: preempted sequences restart first (their
        # prompt now includes generated tokens, so recompute is exact)
        self.waiting.appendleft(seq)

    def finish(self, seq: Sequence) -> None:
        self._release(seq)
        seq.status = SeqStatus.FINISHED

    def _release(self, seq: Sequence) -> None:
        if seq in self.running:
            self.running.remove(seq)
        if seq.lane >= 0:
            self._free_lanes.append(seq.lane)
            seq.lane = -1
        self.allocator.free_sequence(seq.seq_id)
