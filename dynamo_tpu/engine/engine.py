"""JaxLlmEngine — the native TPU inference engine.

Architecture:
- a dedicated **device thread** runs the synchronous scheduler/step loop
  (prefill + batched decode through jitted SPMD functions), keeping the
  asyncio event loop free for network I/O;
- requests enter via the standard streaming-engine interface
  (``generate(Context[dict]) -> ResponseStream[dict]`` speaking
  PreprocessedRequest / Annotated[LLMEngineOutput] wire dicts), so the engine
  drops into the same pipelines as any remote engine;
- static shapes throughout: prompt lengths round up to buckets (one compiled
  prefill per bucket), decode runs a fixed ``max_batch_size`` lane array;
- KV cache is donated through every step (no double-buffering in HBM);
- the allocator publishes stored/removed block events and load metrics for
  the KV-aware router.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import queue as thread_queue
import threading
import time
import uuid
import dataclasses
from dataclasses import dataclass, field
from functools import partial
from typing import AsyncIterator, Callable

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.kv_manager import (
    BlockAllocator,
    KvEvent,
    compute_block_hashes,
)
from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.engine.sequence import Sequence, SeqStatus
from dynamo_tpu.llm.protocols.common import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.models.registry import get_family
from dynamo_tpu.observability import FlightRecorder, StepTelemetry, get_recorder
from dynamo_tpu.observability.perf import UtilizationTracker, model_cost
from dynamo_tpu.robustness.faults import ENGINE_STEP, FAULTS
from dynamo_tpu.ops.sampling import (
    apply_logit_bias,
    apply_penalties,
    sample_tokens,
    token_logprobs,
    topk_logprobs,
)
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged
from dynamo_tpu.utils import knobs

logger = get_logger("engine")


def _round_chunk_tokens(chunk_tokens: int, block_size: int) -> int:
    """Chunk windows round UP to whole blocks (one definition: the sp
    validation and the serving bucket must agree on the number)."""
    return max(1, (chunk_tokens + block_size - 1) // block_size) * block_size


def _kernel_perf_path() -> str:
    """DYN_KERNEL_PERF override or the repo-root KERNEL_PERF.json."""
    import os

    return knobs.get("DYN_KERNEL_PERF") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "KERNEL_PERF.json",
    )


def _ensure_compile_cache() -> str | None:
    """Default-on persistent compile cache.

    An explicitly configured ``jax_compilation_cache_dir`` always wins.
    Otherwise ``DYN_COMPILE_CACHE_DIR`` decides: a path points the cache
    there, ``""`` (empty string) opts out, and unset defaults to
    ``~/.cache/dynamo_tpu/jax_cache`` so AOT-compiled serving programs
    survive worker restarts without any flag.  Returns the active cache
    dir, or None when persistence is disabled.
    """
    import os

    current = jax.config.jax_compilation_cache_dir
    if current:
        return current
    configured = knobs.get("DYN_COMPILE_CACHE_DIR")
    if configured == "":
        return None  # explicit opt-out
    path = configured or os.path.join(
        os.path.expanduser("~"), ".cache", "dynamo_tpu", "jax_cache"
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception as err:  # unwritable HOME etc. — persistence is optional
        logger.info("persistent compile cache unavailable at %s: %s", path, err)
        return None
    return path


def _measured_attention_preference(
    device_kind: str | None = None,
    *,
    batch: int | None = None,
    ctx: int | None = None,
) -> str | None:
    """Consult a measured kernel-perf table (scripts/tpu_validate.py --bench
    → KERNEL_PERF.json at the repo root, or DYN_KERNEL_PERF=path).

    Returns "pallas" or "jax" when a REAL-hardware measurement for this
    platform exists (interpret-mode tables are ignored: Mosaic interpret
    timings say nothing about hardware; tables from a DIFFERENT TPU
    generation are ignored too when ``device_kind`` is known), else None so
    the caller keeps the static heuristic.  Decision: PER-SHAPE when the
    caller passes its decode geometry — the measured paged-attention row
    nearest to (batch, ctx) in log space decides, so a batch-16 engine
    routes to the XLA twin when the batch-16 rows show Pallas losing even
    though batch-64 rows show it winning — else the median speedup across
    all measured shapes.  The table is purely advisory — any malformed
    content degrades to None, never to a startup crash.
    """
    import json
    import math as _math
    import statistics

    explicit = knobs.get("DYN_KERNEL_PERF")
    path = _kernel_perf_path()
    def skip(why: str) -> None:
        # the operator EXPLICITLY pointed here — silently reverting to the
        # static heuristic would look exactly like measured selection
        # working, so every rejection of an explicit table is loud
        if explicit:
            logger.warning(
                "DYN_KERNEL_PERF=%s ignored (%s); using the static "
                "attention heuristic", explicit, why,
            )
        return None

    try:
        with open(path) as f:
            table = json.load(f)
        if table.get("interpret"):
            return skip("recorded in interpret mode")
        if table.get("platform") != "tpu":
            return skip(f"platform {table.get('platform')!r} is not tpu")
        if table.get("calib_ok") is False:
            # the table's own known-FLOPs/known-bytes calibration exceeded
            # device peaks: the timing did not serialize, nothing in it is
            # trustworthy (absent key = older table without calibration)
            return skip("calibration rows exceed device peaks")
        if device_kind and table.get("device_kind") not in (None, device_kind):
            logger.info(
                "kernel-perf table is from %r, this chip is %r; ignoring",
                table.get("device_kind"), device_kind,
            )
            return None
        rows = [
            r for r in table.get("rows", [])
            if r.get("bench") == "paged_attention_decode"
            and "pallas_speedup" in r
        ]
        if not rows:
            return skip("no paged_attention_decode rows")
        if batch is not None:
            shaped = [r for r in rows if "batch" in r and "ctx" in r]
            if shaped:
                def dist(r):
                    d = abs(_math.log2(max(int(r["batch"]), 1) / max(batch, 1)))
                    if ctx is not None:
                        d += 0.5 * abs(
                            _math.log2(max(int(r["ctx"]), 1) / max(ctx, 1))
                        )
                    return d
                nearest = min(shaped, key=dist)
                choice = (
                    "pallas" if float(nearest["pallas_speedup"]) >= 1.0
                    else "jax"
                )
                logger.info(
                    "attention_impl=auto: nearest measured shape "
                    "batch=%s ctx=%s speedup=%.3f -> %s",
                    nearest.get("batch"), nearest.get("ctx"),
                    float(nearest["pallas_speedup"]), choice,
                )
                return choice
        speedups = [float(r["pallas_speedup"]) for r in rows]
    except (OSError, ValueError, TypeError, AttributeError, KeyError) as err:
        return skip(f"unusable: {err}")
    return "pallas" if statistics.median(speedups) >= 1.0 else "jax"


@dataclass
class EngineConfig:
    model: LlamaConfig                 # any registered family's config
    model_family: str = "llama"        # registry key (llama/qwen2/mixtral)
    num_blocks: int = 256
    block_size: int = 16
    max_batch_size: int = 8
    max_model_len: int | None = None
    prefill_buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048, 4096)
    mesh: MeshConfig | None = None
    seed: int = 0
    # KV cache storage dtype: None = model dtype; a jnp dtype, or a string
    # ("fp8" → float8_e4m3fn, "bf16", "f32").  fp8 halves KV bytes — the
    # cache is upcast at every use (attention ops and kernels read through
    # .astype) — doubling the context a chip holds and the decode batch it
    # can run (vLLM's --kv-cache-dtype fp8 equivalent).
    kv_cache_dtype: object = None
    # "auto": Pallas paged-attention kernel on single-chip TPU, gather-based
    # XLA fallback otherwise.  "jax" | "pallas" | "pallas_interpret" force.
    attention_impl: str = "auto"
    # Prefix-cache reuse: completed KV blocks stay resident and matching
    # prompts prefill only the uncached tail (auto-disabled for families
    # without a continued-prefill forward).
    enable_prefix_caching: bool = True
    # Chunked prefill: prompts longer than this prefill in chunks of this
    # many tokens, interleaved with decode steps (None = whole-prompt
    # prefill; rounded up to a block multiple; needs a continued-prefill
    # forward).  Keeps ITL bounded under long-ISL load — the reference
    # relies on engine chunked prefill + disagg offload (SURVEY.md §5).
    prefill_chunk_tokens: int | None = None
    # G2 host-DRAM tier: registered blocks evicted from HBM offload here and
    # restore on a later prefix hit instead of recomputing (0 = off).
    # Reference: block manager G1→G2 offload, lib/llm/src/block_manager/
    # offload.rs:77-80.
    host_offload_blocks: int = 0
    # G3 SSD tier: host-LRU evictions cascade to an np.memmap disk pool and
    # restore from there (0 = off; needs host_offload_blocks > 0).
    disk_offload_blocks: int = 0
    disk_offload_path: str | None = None
    # G4 remote tier: "host:port" of a BlockStoreServer
    # (llm/block_manager/remote.py) — bottom-tier evictions cascade there
    # over DCN and prefix hits restore from it (None = off; needs
    # host_offload_blocks > 0).  Reference: the remote tier of the block
    # manager, lib/llm/src/block_manager.rs:68-81.
    remote_store_addr: str | None = None
    # Predictive prefetch over the offload tiers (prefetch/): hinted
    # prefixes page disk→host→HBM between engine steps, bounded by an HBM
    # headroom reservation so prefetch can never preempt running work, and
    # hot prefixes pin host-resident.  None = DYN_PREFETCH env (default
    # on); only effective when an offload tier is mounted.  DYN_PREFETCH=0
    # restores fully demand-driven paging.
    prefetch: bool | None = None
    # Compile-time K for per-token top-k alternatives (OpenAI
    # top_logprobs caps at 20).  K>0 adds one lax.top_k over [lanes, vocab]
    # to every step (the host transfer of the rows is skipped unless a
    # sequence asked); K=0 removes the compute entirely (top_logprobs
    # requests then get empty alternative rows).
    top_logprobs_k: int = 20
    # Decode iterations fused into one jit launch (lax.scan with device-side
    # token feedback + slot derivation).  >1 amortizes per-step dispatch and
    # host↔device roundtrips — the dominant cost at small batch — at the
    # price of emitting tokens in bursts of this size and wasting up to
    # decode_steps-1 iterations on sequences that hit a stop mid-window.
    decode_steps: int = 1
    # Weight-only quantization ("int8" | None).  The TPU analog of the
    # reference's FP8 headline model (examples/llm/benchmarks/README.md:66):
    # named projection matrices become int8 + per-channel scale
    # (ops/quant.py), halving the HBM bytes every decode step streams.
    # Requires a family with quant_leaves (all registered families).
    quantize: str | None = None
    # Compile-time width of the per-lane OpenAI logit_bias rows (sparse
    # {token: bias} scattered onto the logits each step).  Requests with
    # more entries keep the largest-magnitude ones; 0 disables the scatter.
    logit_bias_k: int = 64
    # Speculative decoding ("ngram" = prompt-lookup self-drafting: the last
    # spec_ngram tokens are matched against the sequence's history and the
    # continuation proposed).  One verify pass scores spec_tokens+1
    # positions per weight stream from HBM — decode is bandwidth-bound, so
    # accepted drafts are nearly free tokens.  Verification is exact: a
    # lane emits beyond one token only while drafts match what plain
    # greedy decode would have produced (sampled/penalized lanes fall back
    # to one token per step).  Composes with decode_steps > 1 (iterations
    # without enough drafts run the fused multi-step program — measured in
    # docs/SPEC_VS_FUSED.json); incompatible with pp.
    speculative: str | None = None
    spec_tokens: int = 4
    spec_ngram: int = 2
    # Overlapped decode pipeline: dispatch the next decode window with
    # ON-DEVICE token feedback (step N+1's input tokens are step N's output
    # array, never a host round-trip) and retire the previous window's
    # results by asynchronous readback while the new one runs — the device
    # never idles waiting on the host half of the loop (double buffering,
    # in-flight depth 1).  The pipeline synchronizes wherever host state
    # genuinely gates the device: batch-composition changes (prefill
    # admission, finishes), preemption, aborts, speculative verify; guided
    # and top_logprobs lanes fall back to the synchronous path per window
    # (their per-token host processing cannot lag the device).  None =
    # DYN_DECODE_OVERLAP env (default on; "0" disables).
    decode_overlap: bool | None = None
    # Ragged unified-batch step: one jitted launch consumes a MIXED token
    # batch — chunked-prefill spans and decode tokens from different
    # sequences, flattened onto one ragged token axis through the ragged
    # paged-attention kernel (ops/pallas/ragged_attention.py, arxiv
    # 2604.15464).  Prefill admission stops being a separate dispatch, so
    # the overlap pipeline no longer drains when a new sequence joins: its
    # first chunk simply rides the next window.  None = DYN_UNIFIED_BATCH
    # env (default ON; "0" disables).  The split prefill/decode path remains
    # compiled
    # and serves as fallback — speculative/guided/multimodal/disagg-prefill
    # lanes keep their current routes, and engines whose geometry the
    # unified step cannot serve (fused decode_steps>1, multi-chip meshes,
    # narrowed KV dtypes, families without a unified forward) auto-disable.
    unified_batch: bool | None = None
    # Minimum fraction of running lanes that must have a draft for the
    # w-wide verify program to run; below it, plain decode serves the step.
    # Cost model (decode is weight-bandwidth-bound): one verify launch
    # streams the weights ONCE (plus the w-wide logits/sampling tax) while
    # a fused plain launch streams them decode_steps times — so a
    # non-drafting lane advances ~1 token per weight stream under EITHER
    # program, and choosing verify costs that lane only the w-wide
    # logits/sampling overhead and per-launch dispatch, not a decode_steps×
    # slowdown.  The fraction gate bounds exactly that overhead: one
    # self-drafting chat request must not tax a whole mixed batch.
    spec_min_fraction: float = 0.25

    def resolved_max_len(self) -> int:
        hard = self.num_blocks * self.block_size
        soft = self.max_model_len or self.model.max_position_embeddings
        return min(soft, self.model.max_position_embeddings, hard)


_KV_DTYPE_NAMES = {
    "fp8": "float8_e4m3fn",
    "float8": "float8_e4m3fn",
    "float8_e4m3fn": "float8_e4m3fn",
    "float8_e5m2": "float8_e5m2",
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
    "f32": "float32",
    "float32": "float32",
    "f16": "float16",
    "float16": "float16",
}


@dataclass
class _InflightWindow:
    """One dispatched-but-unretired decode window (the overlap pipeline's
    in-flight slot).  Everything device-side stays a jax.Array until
    ``_retire_window`` reads it back; ``feedback`` is the final-step token
    array that seeds the NEXT window's input without a host round-trip."""
    tokens: object            # [steps, lanes] (or [lanes] when steps == 1)
    lps: object
    feedback: object          # [lanes] last sampled token per lane
    active: list              # sequences RUNNING at dispatch, lane order
    lane_ids: list            # their lanes (composition fingerprint)
    steps: int
    # sequences whose finish was detected while THIS window was in flight:
    # emitted already, but their lane/blocks are only released when this
    # window retires (a lagged device step may still write into them)
    deferred: list = field(default_factory=list)


def resolve_kv_cache_dtype(spec):
    """None | jnp dtype | string name → dtype usable for cache init."""
    if spec is None or not isinstance(spec, str):
        return spec
    name = _KV_DTYPE_NAMES.get(spec.lower())
    if name is None:
        raise ValueError(
            f"unknown kv_cache_dtype {spec!r} (want one of {sorted(set(_KV_DTYPE_NAMES))})"
        )
    return jnp.dtype(name)


class JaxLlmEngine:
    def __init__(
        self,
        config: EngineConfig,
        params: dict | None = None,
        *,
        event_sink: Callable[[KvEvent], None] | None = None,
    ):
        self.config = config
        cfg = config.model
        _ensure_compile_cache()
        self.family = get_family(config.model_family)
        self.max_len = config.resolved_max_len()
        self.max_blocks_per_seq = (self.max_len + config.block_size - 1) // config.block_size
        self.buckets = sorted({min(b, self.max_len) for b in config.prefill_buckets})
        if self.buckets[-1] < self.max_len:
            self.buckets.append(self.max_len)

        self.mesh = None
        if config.mesh is not None and (
            config.mesh.total() > 1 or config.mesh.device_offset
        ):
            # a 1-device mesh with a device_offset still matters: it pins
            # this engine to a specific device partition (disagg with one
            # chip per role) instead of silently landing on device 0
            self.mesh = make_mesh(config.mesh)
            # static-shape constraints: fail at init, not at first jit
            # trace mid-serving
            if config.mesh.dp > 1:
                # data parallelism in this architecture is worker
                # REPLICATION behind the (KV-aware) router, like the
                # reference — the engine's jits never shard their batch
                # over dp, so a dp axis on an engine mesh would silently
                # replicate compute on every dp shard.  The dp axis exists
                # for model-level callers only (pipeline_layer_stack, the
                # dryrun).
                raise ValueError(
                    f"dp={config.mesh.dp} is not an engine mesh axis: "
                    "scale decode throughput by replicating workers behind "
                    "the router (components/router_service.py), not by "
                    "adding dp to one engine's mesh"
                )
            pp = config.mesh.pp
            if pp > 1:
                # pp composes with the AUTOMATIC GSPMD axes (partial-manual
                # shard_map: pp is the manual stage axis; tp — and ep for
                # MoE families with a pipelined decode — stay automatic
                # inside each stage, parallel/pipeline.py).  sp is
                # prefill-only and has no pipelined variant; dp is never an
                # engine axis (rejected above).
                ep_ok = (
                    config.mesh.ep == 1
                    or (
                        self.family.forward_decode_pp is not None
                        and getattr(cfg, "num_experts", 0) > 1
                    )
                )
                if config.mesh.sp > 1 or not ep_ok:
                    # name only the axes actually at fault (a valid ep on a
                    # MoE family must not appear in the complaint)
                    offending = {}
                    if not ep_ok:
                        offending["ep"] = config.mesh.ep
                    if config.mesh.sp > 1:
                        offending["sp"] = config.mesh.sp
                    raise ValueError(
                        f"pp={pp} composes with tp (all families) and ep "
                        f"(MoE families with a pipelined decode); got "
                        f"{offending} for family {config.model_family!r}"
                    )
                if config.max_batch_size % pp:
                    raise ValueError(
                        f"max_batch_size={config.max_batch_size} must be divisible "
                        f"by the pp axis ({pp}): pipeline microbatches split the "
                        "decode batch evenly"
                    )
                if cfg.num_layers % pp:
                    raise ValueError(
                        f"num_layers={cfg.num_layers} must be divisible by the "
                        f"pp axis ({pp}): layers split evenly into stages"
                    )
            sp = config.mesh.sp
            if sp > 1 and getattr(cfg, "sliding_window", None):
                raise ValueError(
                    "sliding-window attention is incompatible with an sp "
                    "mesh: the ring path has no window mask yet"
                )
            if sp > 1 and not self.family.prefix_prefill_accepts_sp:
                # this family's continued-prefill jit (chunked prefill,
                # prefix hits) runs dense attention only: those modes must
                # not silently bypass the sequence parallelism the mesh
                # was configured for.  (llama-family composes: its prefix
                # forward rings the tail and merges the resident prefix.)
                if config.prefill_chunk_tokens is not None:
                    raise ValueError(
                        "prefill_chunk_tokens is incompatible with an sp "
                        f"mesh for family {config.model_family!r}: its "
                        "continued-prefill path has no ring attention"
                    )
                if config.enable_prefix_caching:
                    logger.warning(
                        "sp mesh: disabling prefix caching (family %r's "
                        "continued-prefill path does not run ring attention)",
                        config.model_family,
                    )
                    config = self.config = dataclasses.replace(
                        config, enable_prefix_caching=False
                    )
            if sp > 1:
                # every sp mesh (chunked or not) rings over padded bucket
                # lengths — fail at construction, not at first jit trace
                bad = [b for b in self.buckets if b % sp]
                if bad:
                    raise ValueError(
                        f"prefill buckets {bad} not divisible by the sp axis "
                        f"({sp}): ring attention shards the sequence evenly"
                    )
                if config.prefill_chunk_tokens is not None:
                    rounded = _round_chunk_tokens(
                        config.prefill_chunk_tokens, config.block_size
                    )
                    if rounded % sp:
                        raise ValueError(
                            f"prefill_chunk_tokens (block-rounded to {rounded}) "
                            f"must be divisible by the sp axis ({sp}): chunk "
                            "windows ring-shard the sequence evenly"
                        )

        if config.attention_impl == "auto":
            # a wedged accelerator plugin must not crash engine construction
            # (this probe was the round-1 bench crash site): fall back to the
            # portable path and let first device use surface the real error
            try:
                backend = jax.default_backend()
            except Exception:  # RuntimeError: unable to initialize backend
                logger.warning("backend probe failed; using gather-based attention")
                backend = "unknown"
            mesh_ok = self.mesh is None or (
                self.family.decode_accepts_tp_mesh
                and all(
                    getattr(config.mesh, a) == 1 for a in ("ep", "sp", "pp")
                )
                # shard_map needs even head sharding; the GSPMD gather path
                # handles uneven tp fine, so fall back there
                and getattr(cfg, "num_kv_heads", 0) % config.mesh.tp == 0
                and getattr(cfg, "num_heads", 0) % config.mesh.tp == 0
            )
            if backend == "tpu" and mesh_ok:
                # a real-hardware kernel-perf table (scripts/tpu_validate.py
                # --bench) outranks the static pallas-on-TPU assumption
                try:
                    kind = jax.devices()[0].device_kind
                except Exception:  # noqa: BLE001
                    kind = None
                measured = _measured_attention_preference(
                    kind, batch=config.max_batch_size, ctx=self.max_len,
                )
                self.attention_impl = measured or "pallas"
                if measured:
                    logger.info(
                        "attention_impl=auto resolved to %r from measured "
                        "kernel-perf table", measured,
                    )
            else:
                self.attention_impl = "jax"
        else:
            self.attention_impl = config.attention_impl

        # All eager init work (param RNG, cache zeros, rope tables) runs on
        # the host CPU backend, then moves to the accelerator with one
        # device_put per leaf.  Eager on-device init was the round-2 bench
        # crash site: every jax.random.normal became a remote-compile RPC.
        try:
            cpu0 = jax.local_devices(backend="cpu")[0]
            host_ctx = jax.default_device(cpu0)
        except Exception:
            host_ctx = contextlib.nullcontext()
        with host_ctx:
            rng = jax.random.PRNGKey(config.seed)
            raw_params = params if params is not None else self.family.init_params(cfg, rng)
            raw_params = self._maybe_quantize(raw_params)
            # sharding specs follow the params tree's CONTENT (a caller may
            # hand in a pre-quantized artifact without setting
            # config.quantize — the spec twin must still match)
            from dynamo_tpu.ops.quant import is_quantized

            self._params_quantized = is_quantized(raw_params)
            raw_cache = self.family.cache_init(
                cfg, config.num_blocks, config.block_size,
                resolve_kv_cache_dtype(config.kv_cache_dtype),
            )
            cos, sin = self.family.rope_tables(cfg)
            # families build tables out to max_position_embeddings (131k for
            # llama3); the engine only ever indexes positions < max_len.
            # Slice before upload — with the full table, every compiled
            # program would carry (and the remote compile service would
            # ship) tens of MB of trig constants.
            cos, sin = cos[: self.max_len], sin[: self.max_len]
            lanes = config.max_batch_size
            gen_counts = jnp.zeros((lanes, cfg.vocab_size), jnp.int32)
            prompt_counts = jnp.zeros((lanes, cfg.vocab_size), jnp.int32)
        # CRITICAL transfer detail: the init-time arrays were built on the
        # host CPU backend (above); handing a CPU-backend jax.Array straight
        # to device_put leaves a cross-backend buffer that some PJRT
        # runtimes (measured on the tunneled axon TPU plugin) re-stage on
        # EVERY program execution that takes it as an argument — ~150ms per
        # such arg per call, which buried the decode loop under ~10x its
        # compute time.  mesh.host_bounce converts such leaves to host
        # ndarrays so device_put yields native, committed device buffers.
        from dynamo_tpu.parallel.mesh import host_bounce

        target_platform = jax.devices()[0].platform
        bounce = lambda x: host_bounce(x, target_platform)  # noqa: E731
        raw_params = jax.tree.map(bounce, raw_params)
        raw_cache = jax.tree.map(bounce, raw_cache)
        cos, sin = bounce(cos), bounce(sin)
        gen_counts = bounce(gen_counts)
        prompt_counts = bounce(prompt_counts)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            param_specs = self.family.param_specs(cfg)
            if self._params_quantized:
                from dynamo_tpu.ops.quant import quantize_specs

                param_specs = quantize_specs(param_specs, self.family.quant_leaves)
            self._param_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), param_specs
            )
            self._cache_sharding = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), self.family.cache_specs(cfg)
            )
            self.params = jax.tree.map(jax.device_put, raw_params, self._param_shardings)
            self.cache = jax.tree.map(jax.device_put, raw_cache, self._cache_sharding)
            repl = NamedSharding(self.mesh, PartitionSpec())
            self.cos = jax.device_put(cos, repl)
            self.sin = jax.device_put(sin, repl)
        else:
            self._param_shardings = None
            self._cache_sharding = None
            self.params = jax.tree.map(jax.device_put, raw_params)
            self.cache = jax.tree.map(jax.device_put, raw_cache)
            self.cos = jax.device_put(cos)
            self.sin = jax.device_put(sin)

        # guided decoding: disabled until enable_guided_json() installs a
        # compiled mask table.  The dummy one-row all-true table keeps the
        # jit signatures stable so enabling guidance never recompiles the
        # unguided programs' SHAPES for lanes that stay unguided (it does
        # change the table aval — enable before warmup).
        self.guided_masks = None
        self._guided_strings: list[str] | None = None
        self._guided_eos: list[int] = []
        self._guided_requests = 0     # guided sequences admitted
        self._guided_completions = 0  # finished with a COMPLETE document
        vocab = cfg.vocab_size
        self._guided_table = jnp.ones((1, vocab), jnp.bool_)
        self._guided_true_row = jnp.ones((vocab,), jnp.bool_)
        if self.mesh is not None:
            self._guided_table = jax.device_put(self._guided_table, repl)
            self._guided_true_row = jax.device_put(self._guided_true_row, repl)

        # per-lane sampling state: generated-token counts (presence/frequency
        # penalties), prompt-token counts (repetition penalty scope), and
        # per-lane PRNG keys (OpenAI `seed` reproducibility).  Lane keys are
        # produced host-side (no device RNG in the request path).
        self._host_rng = np.random.Generator(np.random.PCG64(config.seed))
        self._lane_keys = np.zeros((lanes, 2), np.uint32)

        # Decode hot-loop phase accounting (DYN_ENGINE_PHASE_TIMING=1):
        # wall seconds + counts per phase, surfaced via stats().  Exists
        # because the serving chip can sit behind a high-latency transport
        # (the axon tunnel adds ~6ms per host<->device sync) where the loop's
        # cost profile is unrecognizable vs a local chip — upload/dispatch/
        # readback must be separable from device compute to tune anything.
        self._phase_timing = knobs.get("DYN_ENGINE_PHASE_TIMING")
        self.phase_stats: dict[str, list[float]] = {}
        # Step telemetry: batch occupancy / queue depth / KV pool usage per
        # scheduler iteration, merged into stats() → load-metrics publisher
        # → dyn_worker_* Prometheus gauges (observability.step_metrics).
        self.step_telemetry = StepTelemetry(config.max_batch_size)
        # Utilization accounting (observability/perf.py): the device loop
        # feeds per-step token/context/weight-stream facts; stats() exports
        # rolling MFU / bandwidth-utilization / goodput plus token totals.
        self.utilization = UtilizationTracker(
            model_cost(
                cfg, quantize=config.quantize, kv_cache_dtype=config.kv_cache_dtype
            )
        )
        # Perf flight recorder (observability/flight.py): bounded ring of
        # per-step telemetry + discrete events, dumped to JSONL on demand
        # (dynctl flight dump) or automatically on burn breach / crash /
        # drain.  DYN_FLIGHT=0 makes every hook below a no-op.
        self.flight = FlightRecorder(source="engine")
        self._flight_preemptions = 0    # last preemption total seen, for deltas
        self._tokens_emitted = 0        # tokens that reached a caller's stream
        self._step_prefill_tokens = 0   # per-iteration scratch, reset each step
        self._step_decode_tokens = 0
        self._step_attn_ctx = 0         # sum of attended context positions
        self._step_weight_streams = 0.0 # full weight passes dispatched
        # DYN_XPROF_ANNOTATE=1: wrap hot steps in jax.profiler
        # TraceAnnotation so host-side spans line up with xprof device
        # traces (adds a TraceMe per step — keep off unless profiling)
        self._xprof_annotate = knobs.get("DYN_XPROF_ANNOTATE")
        # DYN_PROFILER_TRACE_DIR: set when start() opened a device trace
        self._profiler_trace_dir: str | None = None
        # Sampling-tail upload cache: the per-window device copies of the
        # (lane_keys, temp, top_k, ...) arrays are reused while their host
        # values are unchanged — at steady-state decode the batch
        # composition changes rarely, and behind a high-RTT transport the
        # ~10 small uploads per window are measurable.  Equality-checked
        # against fresh host arrays every window (cheap), so there is no
        # invalidation bookkeeping to miss.
        self._tail_cache: tuple | None = None
        # Overlapped decode pipeline (see EngineConfig.decode_overlap): the
        # single in-flight window plus counters for stats()/A-B profiling.
        env_overlap = knobs.get("DYN_DECODE_OVERLAP")  # tri-state bool
        if config.decode_overlap is not None:
            self.decode_overlap = bool(config.decode_overlap)
        elif env_overlap is not None:
            self.decode_overlap = env_overlap
        else:
            self.decode_overlap = True
        if self.decode_overlap and config.speculative:
            # drafts are proposed from HOST token history; with windows in
            # flight that history lags the device by a window, so drafts
            # would be mispositioned and verify acceptance would collapse —
            # while every drafting iteration also paid a pipeline drain.
            # The verify program already fuses its own multi-token window;
            # run speculative engines synchronous.
            logger.info("decode overlap disabled: speculative decoding "
                        "drafts from host token history")
            self.decode_overlap = False
        self._inflight: _InflightWindow | None = None
        self._overlap_windows = 0   # windows dispatched with token feedback
        self._sync_windows = 0      # windows served by the synchronous path
        self._decode_steps_total = 0
        # Ragged unified-batch step (EngineConfig.unified_batch): mixed
        # prefill+decode in one launch.  Auto-disables loudly when the
        # engine's geometry cannot serve it — the split path is always the
        # fallback, never a silent behavior change.
        env_unified = knobs.get("DYN_UNIFIED_BATCH")  # tri-state bool
        if config.unified_batch is not None:
            unified = bool(config.unified_batch)
        elif env_unified is not None:
            unified = env_unified
        else:
            # default ON: every registered family with a unified forward
            # serves mixed windows; the auto-disable matrix below downgrades
            # unsupported configs to the split step loudly, never silently
            unified = True
        # unified-batch fallback bookkeeping: reason-slug → count, surfaced
        # in stats() as dyn_worker_unified_fallbacks_total{reason}; each
        # reason logs once per engine (_unified_skip) — the per-step route
        # checks fire every iteration and must not spam
        self._unified_fallbacks: dict[str, int] = {}
        self._unified_fallback_logged: set[str] = set()
        if unified:
            reason = slug = None
            if self.family.forward_unified is None:
                reason = f"family {config.model_family!r} has no unified forward"
                slug = "no_family_forward"
            elif config.speculative:
                reason = "speculative lanes keep their verify route"
                slug = "speculative"
            elif config.decode_steps > 1:
                reason = "fused multi-step decode windows cannot carry chunks"
                slug = "multi_step_decode"
            elif self.mesh is not None:
                reason = "multi-chip meshes keep the split step"
                slug = "mesh"
            else:
                resolved = resolve_kv_cache_dtype(config.kv_cache_dtype)
                if resolved is not None and jnp.dtype(resolved) != jnp.dtype(
                    cfg.dtype
                ) and not jnp.issubdtype(jnp.dtype(resolved), jnp.floating):
                    # float narrowings (fp8/bf16/f16) flow through unified:
                    # every ragged kernel and XLA twin upcasts cache reads
                    # to f32 and write_decode_kv casts on write.  The
                    # parity contract with the split path is tolerance-
                    # level there (split prefill attends full-precision
                    # activations, unified reads its freshly-written
                    # quantized cache) — tests/engine/test_quantized_unified
                    # pins it.  Non-float cache dtypes have no kernel read
                    # path: keep them on the split step, reason-slugged.
                    reason = (
                        f"kv_cache_dtype {config.kv_cache_dtype!r} has no "
                        "unified kernel read path"
                    )
                    slug = "unsupported_kv_dtype"
            if reason is not None:
                self._unified_skip(slug, reason)
                unified = False
        self.unified_batch = unified
        self._unified_windows = 0     # mixed windows served by one dispatch
        self._admission_drains = 0    # pipeline drains forced by admission
        # ragged kernel tunables (token-block size, page-worklist width,
        # pages per grid step), precedence: explicit knob > tuned
        # KERNEL_PERF.json row (ops/autotune.py) > heuristic default.
        # tb: the flat token axis pads to whole kernel blocks of this many
        # tokens; lanes PACK within a block (per-row routing), so this is
        # launch-grid granularity only.  ps: static worklist width — ONE
        # shape per token bucket, so compiles (and AOT warming) never churn
        # on batch composition; the full width (tb * max_blocks_per_seq)
        # always fits, a tuned tighter width falls back to it through the
        # overflow repack ladder in _run_unified.
        import math as _math

        tb_default = _math.gcd(config.block_size, 8) or 1
        tuned = self._resolve_tuned_kernel_config(cfg)
        knob_tb = knobs.get("DYN_AUTOTUNE_TB")
        knob_ps = knobs.get("DYN_AUTOTUNE_PAGE_SLOTS")
        knob_pps = knobs.get("DYN_AUTOTUNE_PAGES_PER_STEP")
        # a tb that cannot pack every unified bucket would split-fallback
        # every window: validate tuned/knob choices against the prospective
        # bucket set (chunk + mixed buckets are added below, after this)
        prospective = set(self.buckets)
        if (
            config.prefill_chunk_tokens is not None
            and self.family.forward_prefill_with_prefix is not None
        ):
            ct = _round_chunk_tokens(
                config.prefill_chunk_tokens, config.block_size
            )
            if ct < self.max_len:
                prospective.add(ct)
                mixed_b = -(-(ct + config.max_batch_size) // 8) * 8
                if mixed_b < self.max_len:
                    prospective.add(mixed_b)
        tb = int(knob_tb or (tuned or {}).get("tb_tokens") or tb_default)
        if tb != tb_default and any(b % tb for b in prospective):
            logger.warning(
                "kernel tb_tokens=%d does not divide unified buckets %s; "
                "using heuristic default %d",
                tb, sorted(prospective), tb_default,
            )
            tb = tb_default
        tuned_fits = tuned is not None and int(tuned["tb_tokens"]) == tb
        pps = int(
            knob_pps
            or ((tuned or {}).get("pages_per_step") if tuned_fits else 0)
            or 1
        )
        ps_full = tb * self.max_blocks_per_seq
        pps = max(1, min(pps, ps_full))
        ps = int(
            knob_ps
            or ((tuned or {}).get("page_slots") if tuned_fits else 0)
            or ps_full
        )
        # kernel contract: page_slots is a positive multiple of
        # pages_per_step; the overflow ladder's full width too
        ps = -(-max(pps, min(ps, ps_full)) // pps) * pps
        self._unified_tb = tb
        self._unified_ps = ps
        self._unified_pps = pps
        self._unified_ps_full = -(-ps_full // pps) * pps
        self._unified_ps_overflows = 0  # windows repacked at full width
        if knob_tb or knob_ps or knob_pps:
            source = "knob"
        elif tuned_fits:
            source = "tuned"
        else:
            source = "default"
        self._kernel_config = {
            "tb_tokens": tb, "page_slots": ps, "pages_per_step": pps,
            "source": source,
            "geometry": getattr(self, "_kernel_geometry", None),
        }
        if source != "default":
            logger.info(
                "unified kernel config (%s): tb_tokens=%d page_slots=%d "
                "pages_per_step=%d", source, tb, ps, pps,
            )
        self._fb_zero = None          # resident all-zero feedback tokens
        self._seed_none = None        # resident no-op seed scatter args
        # Per-lane block-table host rows, rewritten only for lanes whose
        # block list changed since the last window; the device copy is
        # reused untouched while every row is clean.  At steady-state
        # decode a lane's table changes once per block_size tokens, so the
        # (lanes × max_blocks_per_seq) rebuild+upload the old loop paid
        # every step (decode.upload in the profile) collapses to nothing.
        lanes_n = config.max_batch_size
        self._bt_host = np.zeros((lanes_n, self.max_blocks_per_seq), np.int32)
        self._bt_lane_key: list = [None] * lanes_n
        self._bt_dev = None
        # overlap windows carry no guided lanes (they fall back to sync):
        # one resident all-unguided mode row, uploaded once
        self._gmodes_unguided = None
        if self.mesh is not None:
            self._gen_counts = jax.device_put(gen_counts, repl)
            self._prompt_counts = jax.device_put(prompt_counts, repl)
        else:
            self._gen_counts = jax.device_put(gen_counts)
            self._prompt_counts = jax.device_put(prompt_counts)

        self.prefix_caching = (
            config.enable_prefix_caching
            and self.family.forward_prefill_with_prefix is not None
        )
        self.chunk_tokens = None
        if (
            config.prefill_chunk_tokens is not None
            and self.family.forward_prefill_with_prefix is not None
        ):
            self.chunk_tokens = _round_chunk_tokens(
                config.prefill_chunk_tokens, config.block_size
            )
            # chunks run as their own compile bucket (otherwise every chunk
            # pads up to the next full-prompt bucket)
            if self.chunk_tokens < self.max_len:
                self.buckets = sorted(set(self.buckets) | {self.chunk_tokens})
                if self.unified_batch:
                    # the steady-state MIXED window is a full chunk plus one
                    # decode token per lane: give it its own bucket too, or
                    # every unified window pads up to the next prompt bucket.
                    # Decode lanes PACK into shared kernel token blocks on
                    # both attention paths, so each costs exactly one slot.
                    mixed = -(-(
                        self.chunk_tokens + self.config.max_batch_size
                    ) // 8) * 8
                    if mixed < self.max_len:
                        self.buckets = sorted(set(self.buckets) | {mixed})
        self.host_tier = None
        self._host_evictions: list[int] | None = None
        offload_sink = None
        if config.host_offload_blocks and self.prefix_caching:
            from dynamo_tpu.engine.offload import HostOffloadTier

            leaves = dict(self.cache)
            self.host_tier = HostOffloadTier(
                config.host_offload_blocks,
                {k: (v.shape[0], *v.shape[2:]) for k, v in leaves.items()},
                {k: np.dtype(v.dtype) for k, v in leaves.items()},
                disk_blocks=config.disk_offload_blocks,
                disk_path=config.disk_offload_path,
                remote_addr=config.remote_store_addr,
            )
            offload_sink = self._offload_blocks
            # a hash that left EVERY tier (fell off the bottom of the
            # G2→G3→G4 cascade) while no longer device-resident: routers
            # must forget it
            self.host_tier.evict_observer = self._host_evicted
        elif (
            config.host_offload_blocks
            or config.disk_offload_blocks
            or config.remote_store_addr
        ):
            # a silently-ignored tier config is worse than a loud one: the
            # operator believes offload is on while nothing mounts
            raise ValueError(
                "KV offload tiers configured but unusable: "
                + (
                    "disk/remote tiers need host_offload_blocks > 0"
                    if not config.host_offload_blocks
                    else "this model family/config has no prefix caching"
                )
            )
        self.allocator = BlockAllocator(
            config.num_blocks, config.block_size, event_sink=self._sink_event,
            enable_prefix_caching=self.prefix_caching,
            offload_sink=offload_sink, host_tier=self.host_tier,
        )
        # predictive prefetch: pager + HBM headroom reservation (only with
        # an offload tier mounted — with nothing below HBM there is nothing
        # to page in ahead of time)
        self.prefetch_pager = None
        self._prefetch_headroom_blocks = 0
        if self.host_tier is not None:
            from dynamo_tpu.prefetch.hints import prefetch_enabled
            from dynamo_tpu.prefetch.pager import PrefetchPager

            enabled = (
                config.prefetch if config.prefetch is not None
                else prefetch_enabled()
            )
            if enabled:
                from dynamo_tpu.observability import TraceContext

                self.prefetch_pager = PrefetchPager(
                    ttl_s=knobs.get("DYN_PREFETCH_TTL"),
                    blocks_per_step=knobs.get("DYN_PREFETCH_BLOCKS"),
                )
                self._prefetch_trace = TraceContext.new_root()
                self.allocator.prefetch_tracker = self.prefetch_pager
                headroom_frac = knobs.get("DYN_PREFETCH_HEADROOM")
                self._prefetch_headroom_blocks = max(
                    self.allocator.watermark_blocks,
                    int(config.num_blocks * headroom_frac),
                )
            # nothing drains pin candidates without the pager, and
            # DYN_PREFETCH=0 must be bookkeeping-free demand paging
            self.host_tier.pin_enabled = self.prefetch_pager is not None
        self.scheduler = Scheduler(
            self.allocator, max_batch_size=config.max_batch_size,
            prefill_chunk_tokens=self.chunk_tokens,
            bucket_cost=self._bucket_len,
            unified_batch=self.unified_batch,
        )
        self.scheduler.on_preempt = self._on_preempt
        self._event_sink = event_sink
        self._iterations = 0

        # thread plumbing
        self._submit_q: thread_queue.Queue = thread_queue.Queue()
        self._wake = threading.Event()
        self._stop = False
        self._thread: threading.Thread | None = None
        self._jit_prefill = self._build_prefill()
        self._jit_prefill_prefix = (
            self._build_prefill_prefix()
            if (self.prefix_caching or self.chunk_tokens is not None)
            else None
        )
        self._jit_prefill_mm = (
            self._build_prefill_mm()
            if self.family.forward_prefill_embeds is not None
            else None
        )
        self._jit_decode = self._build_decode()
        # unified window seed capacity: only NEWLY-ADMITTED prefills need
        # their penalty-count rows (re)seeded, and admission is bounded by
        # the scheduler's per-step cap
        self._unified_seed_slots = max(1, self.scheduler.max_prefills_per_step)
        self._jit_unified = self._build_unified() if self.unified_batch else None
        self.spec_enabled = bool(config.speculative)
        if self.spec_enabled:
            if config.speculative != "ngram":
                raise ValueError(
                    f"unknown speculative mode {config.speculative!r} (want 'ngram')"
                )
            if self.family.forward_verify is None:
                raise ValueError(
                    f"model family {config.model_family!r} has no verification "
                    "forward (speculative decoding unsupported)"
                )
            # decode_steps > 1 COMPOSES with speculation: iterations where
            # enough lanes drafted run the verify program (its window
            # already fuses up to spec_tokens+1 tokens per launch); the
            # rest — sampled/penalized lanes, draft misses — run the fused
            # multi-step decode program instead of single-token launches.
            # Measured on both regimes: scripts/spec_vs_fused.py →
            # docs/SPEC_VS_FUSED.json.
            if config.mesh is not None and config.mesh.pp > 1:
                raise ValueError("speculative decoding does not support pp meshes")
            if config.spec_tokens < 1:
                raise ValueError("spec_tokens must be >= 1")
            if config.spec_ngram < 1:
                raise ValueError("spec_ngram must be >= 1")
        self._jit_verify = self._build_verify() if self.spec_enabled else None
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._jit_extract = self._build_extract()
        # block-table compile buckets (id-array lengths for extract/inject/
        # restore/prefix paths — no full-size pad buffers)
        self._table_buckets = sorted(
            {self.allocator.blocks_needed(b) for b in self.buckets}
            | {self.max_blocks_per_seq}
        )
        self._jit_inject = self._build_inject()
        set_row_kwargs = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            set_row_kwargs["out_shardings"] = NamedSharding(self.mesh, PartitionSpec())
        self._jit_set_row = jax.jit(
            lambda counts, lane, row: counts.at[lane].set(row),
            donate_argnums=(0,), **set_row_kwargs,
        )

    def _maybe_quantize(self, raw_params: dict) -> dict:
        """Apply EngineConfig.quantize to a (host-resident) param tree.
        Pre-quantized trees (e.g. loaded from a quantized artifact) pass
        through untouched."""
        if not self.config.quantize:
            return raw_params
        if self.config.quantize != "int8":
            raise ValueError(
                f"unknown quantize mode {self.config.quantize!r} (want 'int8')"
            )
        if not self.family.quant_leaves:
            raise ValueError(
                f"model family {self.config.model_family!r} does not support "
                "weight-only quantization (no quant_leaves)"
            )
        from dynamo_tpu.ops.quant import is_quantized, quantize_params

        if is_quantized(raw_params):
            return raw_params
        return quantize_params(raw_params, self.family.quant_leaves)

    def _resolve_tuned_kernel_config(self, cfg) -> dict | None:
        """Look up the autotuned ragged-kernel row for this engine's
        (geometry, device_kind, kv dtype) in the kernel-perf table
        (DYN_KERNEL_PERF or repo-root KERNEL_PERF.json).  Advisory like the
        attention-impl lookup: anything malformed degrades to None (the
        heuristic defaults), never to a startup crash.  DYN_AUTOTUNE=0
        disables the lookup entirely."""
        self._kernel_geometry = None
        if knobs.get("DYN_AUTOTUNE") is False:
            return None
        try:
            from dynamo_tpu.ops import autotune as _autotune

            heads = int(getattr(cfg, "num_heads", 0) or 1)
            geom = _autotune.Geometry(
                num_heads=heads,
                num_kv_heads=int(getattr(cfg, "num_kv_heads", 0) or heads),
                head_dim=int(
                    getattr(cfg, "head_dim", 0)
                    or getattr(cfg, "kv_lora_rank", 0)
                    or 128
                ),
                block_size=self.config.block_size,
                lanes=self.config.max_batch_size,
                max_blocks_per_seq=self.max_blocks_per_seq,
            )
            kv_dtype = resolve_kv_cache_dtype(self.config.kv_cache_dtype)
            if kv_dtype is None:
                kv_dtype = jnp.dtype(cfg.dtype)
            try:
                kind = jax.devices()[0].device_kind
            except Exception:  # noqa: BLE001
                kind = None
            self._kernel_geometry = geom.key
            return _autotune.resolve(
                _autotune.load_table(_kernel_perf_path()),
                geometry_key=geom.key,
                device_kind=kind,
                dtype=str(jnp.dtype(kv_dtype)),
            )
        except Exception as err:  # noqa: BLE001
            logger.warning("autotune table resolution failed: %s", err)
            return None

    # -- guided decoding ---------------------------------------------------
    def enable_guided_json(self, tokenizer) -> None:
        """Install the compiled JSON admissible-token table for guided
        requests (``output_format="json"``).  Call before warmup so the
        table's aval is part of the AOT-compiled programs.

        Vocab-size note: model vocabs are often padded past the tokenizer
        vocab; padding columns are masked False (a padded id is never a
        valid JSON continuation)."""
        from dynamo_tpu.llm.guided import build_for_tokenizer

        masks, strings = build_for_tokenizer(tokenizer)
        self.set_guided(masks, strings, tokenizer.eos_token_ids)

    def set_guided(self, masks, strings: list[str], eos_ids: list[int]) -> None:
        """Lower-level install (tests / pre-built tables)."""
        vocab = self.config.model.vocab_size
        table = np.zeros((masks.mask.shape[0], vocab), bool)
        table[:, : masks.mask.shape[1]] = masks.mask[:, :vocab]
        self.guided_masks = masks
        self._guided_strings = strings
        self._guided_eos = list(eos_ids)
        table_j = jnp.asarray(table)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            table_j = jax.device_put(
                table_j, NamedSharding(self.mesh, PartitionSpec())
            )
        self._guided_table = table_j

    def _guided_row(self, seq) -> jnp.ndarray:
        """The prefill-time mask row for one sequence (all-true when the
        sequence is unguided or its cursor bailed out)."""
        if seq.guided is None or seq.guided.mode_id < 0:
            return self._guided_true_row
        return self._guided_table[seq.guided.mode_id]

    # -- jitted steps ------------------------------------------------------
    def _build_prefill(self):
        cfg = self.config.model
        vocab = cfg.vocab_size
        topk_k = self.config.top_logprobs_k

        # sequence parallelism: prefill attention rides the ring kernel when
        # the mesh has an sp axis and the family supports it
        prefill_kwargs = {}
        if (
            self.mesh is not None
            and self.mesh.shape.get("sp", 1) > 1
            and self.family.supports_sp
        ):
            prefill_kwargs["sp_mesh"] = self.mesh

        # cos/sin ride as arguments, not closure constants: a closed-over
        # concrete array is baked into the HLO as a constant (observed:
        # 350MB of trig tables inside one compiled prefill program, which
        # is what the remote compile service chokes on)
        def step(params, cache, gen_counts, prompt_counts, lane, token_ids,
                 block_ids, seq_len, start_pos, gen_row, key, temp, top_k, top_p,
                 greedy, pres, freq, rep, bias_ids, bias_vals, grow, cos, sin):
            logits, cache = self.family.forward_prefill(
                params, cfg, token_ids, cache, block_ids, seq_len, start_pos,
                cos, sin, **prefill_kwargs,
            )
            # (re)seed this lane's sampling state.  ``gen_row`` is the count
            # of already-generated tokens (nonzero only on preemption
            # recompute, where token_ids = prompt + generated): subtracting
            # it keeps prompt vs generated counts exact, so presence/
            # frequency penalties and seeded sampling survive preemption.
            seq_pad = token_ids.shape[0]
            valid = (jnp.arange(seq_pad) < seq_len).astype(jnp.int32)
            full_row = jnp.zeros((vocab,), jnp.int32).at[token_ids].add(valid, mode="drop")
            prompt_row = full_row - gen_row
            prompt_counts = prompt_counts.at[lane].set(prompt_row)
            gen_counts = gen_counts.at[lane].set(gen_row)
            plogits = apply_penalties(
                logits[None], gen_row[None], prompt_row[None], pres, freq, rep
            )
            plogits = apply_logit_bias(plogits, bias_ids, bias_vals)
            # guided decoding: inadmissible tokens → -inf (all-true row for
            # unguided sequences)
            plogits = jnp.where(grow[None], plogits, -jnp.inf)
            step_key = jax.random.fold_in(key, seq_len)
            token = sample_tokens(plogits, step_key[None], temp, top_k, top_p, greedy)[0]
            lp = token_logprobs(plogits, token[None])[0]
            tk_vals, tk_ids = topk_logprobs(plogits, topk_k)
            gen_counts = gen_counts.at[lane, token].add(1)
            return token, lp, tk_vals[0], tk_ids[0], cache, gen_counts, prompt_counts

        kwargs = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            kwargs["out_shardings"] = (repl, repl, repl, repl, self._cache_sharding, repl, repl)
        return jax.jit(step, donate_argnums=(1, 2, 3), **kwargs)

    def _build_prefill_prefix(self):
        """Continued prefill over a resident prefix (prefix-cache hit or a
        later chunk of a chunked prefill).  Penalty rows come in from the
        host (the full prompt is not on device here) and the sampling key
        folds with the total context length so seeded sampling matches the
        uncached path exactly."""
        cfg = self.config.model
        topk_k = self.config.top_logprobs_k

        # sequence parallelism: the tail rings over the sp axis with the
        # resident prefix merged per shard (same gate as _build_prefill)
        prefix_kwargs = {}
        if (
            self.mesh is not None
            and self.mesh.shape.get("sp", 1) > 1
            and self.family.prefix_prefill_accepts_sp
        ):
            prefix_kwargs["sp_mesh"] = self.mesh

        def step(params, cache, gen_counts, prompt_counts, lane, token_ids,
                 full_block_ids, tail_block_ids, tail_len, start_pos, total_len,
                 prompt_row, gen_row, sample_gate, key, temp, top_k, top_p,
                 greedy, pres, freq, rep, bias_ids, bias_vals, grow, cos, sin):
            logits, cache = self.family.forward_prefill_with_prefix(
                params, cfg, token_ids, cache, full_block_ids, tail_block_ids,
                tail_len, start_pos, cos, sin, **prefix_kwargs,
            )
            prompt_counts = prompt_counts.at[lane].set(prompt_row)
            gen_counts = gen_counts.at[lane].set(gen_row)
            plogits = apply_penalties(
                logits[None], gen_row[None], prompt_row[None], pres, freq, rep
            )
            plogits = apply_logit_bias(plogits, bias_ids, bias_vals)
            plogits = jnp.where(grow[None], plogits, -jnp.inf)
            step_key = jax.random.fold_in(key, total_len)
            token = sample_tokens(plogits, step_key[None], temp, top_k, top_p, greedy)[0]
            lp = token_logprobs(plogits, token[None])[0]
            tk_vals, tk_ids = topk_logprobs(plogits, topk_k)
            # sample_gate=0 for non-final chunks of a chunked prefill: the
            # logits are discarded and no generated count is recorded
            gen_counts = gen_counts.at[lane, token].add(sample_gate)
            return token, lp, tk_vals[0], tk_ids[0], cache, gen_counts, prompt_counts

        kwargs = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            kwargs["out_shardings"] = (repl, repl, repl, repl, self._cache_sharding, repl, repl)
        return jax.jit(step, donate_argnums=(1, 2, 3), **kwargs)

    def _build_prefill_mm(self):
        """Multimodal prefill: input embeddings are vision patch embeddings
        (positions < n_patch) spliced before text token embeddings looked up
        in-jit.  (Reference: multimodal encode→prefill flow,
        examples/multimodal/components/encode_worker.py:61.)"""
        cfg = self.config.model
        vocab = cfg.vocab_size
        topk_k = self.config.top_logprobs_k

        def step(params, cache, gen_counts, prompt_counts, lane, embeds,
                 token_ids, n_patch, block_ids, seq_len, gen_row, key, temp,
                 top_k, top_p, greedy, pres, freq, rep, bias_ids, bias_vals,
                 grow, cos, sin):
            s = token_ids.shape[0]
            pos = jnp.arange(s)
            # the family's embed hook carries input-embedding quirks (gemma
            # scales by sqrt(hidden)) so this generic splice code never
            # copies family math inline
            if self.family.embed is not None:
                x_text = self.family.embed(params, cfg, token_ids)
            else:
                x_text = params["embed"][token_ids].astype(cfg.dtype)
            x = jnp.where((pos < n_patch)[:, None], embeds.astype(cfg.dtype), x_text)
            logits, cache = self.family.forward_prefill_embeds(
                params, cfg, x, cache, block_ids, seq_len, jnp.int32(0),
                cos, sin,
            )
            # penalty rows count TEXT tokens only (patch positions masked)
            valid = ((pos >= n_patch) & (pos < seq_len)).astype(jnp.int32)
            full_row = jnp.zeros((vocab,), jnp.int32).at[token_ids].add(valid, mode="drop")
            prompt_row = full_row - gen_row
            prompt_counts = prompt_counts.at[lane].set(prompt_row)
            gen_counts = gen_counts.at[lane].set(gen_row)
            plogits = apply_penalties(
                logits[None], gen_row[None], prompt_row[None], pres, freq, rep
            )
            plogits = apply_logit_bias(plogits, bias_ids, bias_vals)
            plogits = jnp.where(grow[None], plogits, -jnp.inf)
            step_key = jax.random.fold_in(key, seq_len)
            token = sample_tokens(plogits, step_key[None], temp, top_k, top_p, greedy)[0]
            lp = token_logprobs(plogits, token[None])[0]
            tk_vals, tk_ids = topk_logprobs(plogits, topk_k)
            gen_counts = gen_counts.at[lane, token].add(1)
            return token, lp, tk_vals[0], tk_ids[0], cache, gen_counts, prompt_counts

        kwargs = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            kwargs["out_shardings"] = (repl, repl, repl, repl, self._cache_sharding, repl, repl)
        return jax.jit(step, donate_argnums=(1, 2, 3), **kwargs)

    def _build_decode(self):
        cfg = self.config.model
        steps = self.config.decode_steps
        topk_k = self.config.top_logprobs_k

        # pipeline parallelism: when the mesh has a pp axis and the family
        # ships a pipelined decode, the layer stack runs as GPipe-style
        # stages over ICI instead of a plain scan (parallel/pipeline.py)
        use_pp = (
            self.mesh is not None
            and self.mesh.shape.get("pp", 1) > 1
            and self.family.forward_decode_pp is not None
        )

        def fwd_decode(params, cache, tokens, tables, lens, slots, cos, sin):
            if use_pp:
                return self.family.forward_decode_pp(
                    params, cfg, tokens, cache, tables, lens, slots,
                    cos, sin, pp_mesh=self.mesh,
                )
            kwargs = {"attention": self.attention_impl}
            if (
                self.mesh is not None
                and self.attention_impl.startswith("pallas")
                and self.family.decode_accepts_tp_mesh
            ):
                # the pallas kernel runs per tp shard under shard_map
                kwargs["tp_mesh"] = self.mesh
            return self.family.forward_decode(
                params, cfg, tokens, cache, tables, lens, slots,
                cos, sin, **kwargs,
            )

        lanes = self.config.max_batch_size
        lane_idx = jnp.arange(lanes)

        kwargs = {}
        repl = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())

        if steps <= 1:
            if repl is not None:
                kwargs["out_shardings"] = (
                    repl, repl, repl, repl, self._cache_sharding, repl
                )
            def step(params, cache, gen_counts, prompt_counts, token_ids,
                     block_tables, context_lens, slot_ids, keys, temp, top_k,
                     top_p, greedy, pres, freq, rep, bias_ids, bias_vals,
                     gtable, gmodes, cos, sin):
                logits, cache = fwd_decode(
                    params, cache, token_ids, block_tables, context_lens,
                    slot_ids, cos, sin,
                )
                logits = apply_penalties(logits, gen_counts, prompt_counts, pres, freq, rep)
                logits = apply_logit_bias(logits, bias_ids, bias_vals)
                # guided decoding: each lane's mode id selects its
                # admissible-token row from the resident table; mode -1 =
                # unguided (all tokens allowed)
                rows = gtable[jnp.clip(gmodes, 0, gtable.shape[0] - 1)]
                allowed = jnp.where((gmodes < 0)[:, None], True, rows)
                logits = jnp.where(allowed, logits, -jnp.inf)
                step_keys = jax.vmap(jax.random.fold_in)(keys, context_lens)
                tokens = sample_tokens(logits, step_keys, temp, top_k, top_p, greedy)
                lps = token_logprobs(logits, tokens)
                tk_vals, tk_ids = topk_logprobs(logits, topk_k)
                active = (context_lens > 0).astype(jnp.int32)
                gen_counts = gen_counts.at[lane_idx, tokens].add(active)
                return tokens, lps, tk_vals, tk_ids, cache, gen_counts

            return jax.jit(step, donate_argnums=(1, 2), **kwargs)

        # Fused multi-step decode: scan `steps` iterations on-device.  The
        # sampled token feeds back without a host roundtrip; per-iteration
        # cache slots are derived from the (pre-extended) block tables.
        block_size = self.config.block_size
        oob = self.config.num_blocks * block_size
        max_pos = self.max_len - 1

        def multi(params, cache, gen_counts, prompt_counts, token_ids,
                  block_tables, context_lens, keys, temp, top_k, top_p, greedy,
                  pres, freq, rep, bias_ids, bias_vals, cos, sin):
            active = context_lens > 0
            active_i = active.astype(jnp.int32)

            def body(carry, _):
                tokens, cache, gen_counts, lens = carry
                # block tables cover the window; overflow past max_len is
                # clamped (garbage written to the final slot is discarded by
                # the host's LENGTH finish)
                pos = jnp.clip(lens - 1, 0, max_pos)
                blk = jnp.take_along_axis(block_tables, (pos // block_size)[:, None], axis=1)[:, 0]
                slots = jnp.where(active, blk * block_size + pos % block_size, oob)
                logits, cache = fwd_decode(
                    params, cache, tokens, block_tables, lens, slots, cos, sin
                )
                logits = apply_penalties(logits, gen_counts, prompt_counts, pres, freq, rep)
                logits = apply_logit_bias(logits, bias_ids, bias_vals)
                step_keys = jax.vmap(jax.random.fold_in)(keys, lens)
                tokens = sample_tokens(logits, step_keys, temp, top_k, top_p, greedy)
                lps = token_logprobs(logits, tokens)
                tk_vals, tk_ids = topk_logprobs(logits, topk_k)
                gen_counts = gen_counts.at[lane_idx, tokens].add(active_i)
                lens = jnp.where(active, lens + 1, lens)
                return (tokens, cache, gen_counts, lens), (tokens, lps, tk_vals, tk_ids)

            (tokens_last, cache, gen_counts, _), (tokens_seq, lp_seq, tkv_seq, tki_seq) = jax.lax.scan(
                body, (token_ids, cache, gen_counts, context_lens), None, length=steps
            )
            # the carry tokens ride out as a dedicated output: the overlap
            # pipeline feeds them straight back as the next window's input
            # (one extra output handle beats a separate slice launch)
            return tokens_seq, lp_seq, tkv_seq, tki_seq, tokens_last, cache, gen_counts

        if repl is not None:
            # one extra leading repl vs the single-step tuple: the
            # dedicated feedback-tokens output
            kwargs["out_shardings"] = (
                repl, repl, repl, repl, repl, self._cache_sharding, repl
            )
        return jax.jit(multi, donate_argnums=(1, 2), **kwargs)

    def _build_unified(self):
        """Ragged unified-batch step: ONE launch computes chunked-prefill
        spans and decode tokens from different sequences (flat token axis +
        per-token lane/pos metadata + packed page worklist, forward_unified
        → ragged paged attention), then samples one token per lane.  Key-fold, penalty, bias and
        guided-free logits math mirror the split programs bit-for-bit so
        the two paths keep byte-identical outputs:

        - ``context_lens[lane]`` doubles as the attention context AND the
          per-lane key fold value (split prefill folds with the total
          length, split decode with the context including the new token —
          both equal the lane's span end);
        - newly-admitted prefills (re)seed their penalty-count rows in-jit
          via the ``seed_*`` scatter, exactly what the split prefill
          programs compute from the prompt;
        - ``sample_gate`` drops intermediate-chunk samples from the
          generated counts, like the continued-prefill program's gate.

        Single-device only (the engine auto-disables unified on meshes)."""
        cfg = self.config.model
        topk_k = self.config.top_logprobs_k
        lanes = self.config.max_batch_size
        tb = self._unified_tb
        lane_idx = jnp.arange(lanes)

        def step(params, cache, gen_counts, prompt_counts, token_ids,
                 feedback, use_fb, block_tables, context_lens, token_pos,
                 token_slot, token_lane, page_phys, page_lane, page_ord,
                 page_count, sample_rows, sample_gate, seed_lanes,
                 seed_prompt, seed_gen, keys, temp, top_k, top_p, greedy,
                 pres, freq, rep, bias_ids, bias_vals, cos, sin):
            lane_c = jnp.clip(token_lane, 0, lanes - 1)
            # on-device token feedback: a decode token whose lane has an
            # unretired window reads the previous window's output array —
            # the host never waits for (or sees) the token it dispatches
            tok = jnp.where(use_fb, feedback[lane_c], token_ids)
            logits, cache = self.family.forward_unified(
                params, cfg, tok, cache, block_tables, context_lens,
                token_pos, token_slot, token_lane, page_phys, page_lane,
                page_ord, page_count, sample_rows, cos, sin,
                attention=self.attention_impl, tb_tokens=tb,
                pages_per_step=self._unified_pps,
            )  # [lanes, vocab]
            prompt_counts = prompt_counts.at[seed_lanes].set(
                seed_prompt, mode="drop"
            )
            gen_counts = gen_counts.at[seed_lanes].set(seed_gen, mode="drop")
            plogits = apply_penalties(
                logits, gen_counts, prompt_counts, pres, freq, rep
            )
            plogits = apply_logit_bias(plogits, bias_ids, bias_vals)
            step_keys = jax.vmap(jax.random.fold_in)(keys, context_lens)
            tokens = sample_tokens(plogits, step_keys, temp, top_k, top_p, greedy)
            lps = token_logprobs(plogits, tokens)
            tk_vals, tk_ids = topk_logprobs(plogits, topk_k)
            gen_counts = gen_counts.at[lane_idx, tokens].add(sample_gate)
            return tokens, lps, tk_vals, tk_ids, cache, gen_counts, prompt_counts

        return jax.jit(step, donate_argnums=(1, 2, 3))

    def _build_verify(self):
        """Speculative verification step: one forward over the [lanes, w]
        window (w = spec_tokens + 1), position 0 through the full sampling
        machinery, later positions greedy.  Lanes verify drafts with the
        leading-match rule; ``spec_ok`` gates lanes whose sampling config
        makes greedy verification exact (greedy, no penalties)."""
        cfg = self.config.model
        topk_k = self.config.top_logprobs_k
        w_len = self.config.spec_tokens + 1
        lanes = self.config.max_batch_size
        lane_idx = jnp.arange(lanes)

        def step(params, cache, gen_counts, prompt_counts, token_ids,
                 block_tables, context_lens, slot_ids, spec_ok, keys, temp,
                 top_k, top_p, greedy, pres, freq, rep, bias_ids, bias_vals,
                 cos, sin):
            # the pallas window kernel runs single-device only (the tp
            # shard_map wrapper exists just for the 1-query kernel)
            impl = self.attention_impl if self.mesh is None else "jax"
            logits, cache = self.family.forward_verify(
                params, cfg, token_ids, cache, block_tables, context_lens,
                slot_ids, cos, sin, attention=impl,
            )  # [lanes, w, vocab]
            active = context_lens > 0
            base_lens = jnp.maximum(context_lens - (w_len - 1), 0)
            step_keys = jax.vmap(jax.random.fold_in)(keys, base_lens)

            outs, lps, tkvs, tkis = [], [], [], []
            for i in range(w_len):
                li = apply_penalties(
                    logits[:, i], gen_counts, prompt_counts, pres, freq, rep
                )
                li = apply_logit_bias(li, bias_ids, bias_vals)
                if i == 0:
                    ti = sample_tokens(li, step_keys, temp, top_k, top_p, greedy)
                else:
                    ti = jnp.argmax(li, axis=-1).astype(jnp.int32)
                outs.append(ti)
                lps.append(token_logprobs(li, ti))
                tv, tk_ = topk_logprobs(li, topk_k)
                tkvs.append(tv)
                tkis.append(tk_)
            tokens_out = jnp.stack(outs, axis=1)       # [lanes, w]
            lp_out = jnp.stack(lps, axis=1)
            tkv_out = jnp.stack(tkvs, axis=1)
            tki_out = jnp.stack(tkis, axis=1)

            # leading-match acceptance: draft i (window token i) is kept iff
            # every earlier draft matched and it equals the model's output
            # at position i-1
            acc = spec_ok & active
            n_accept = jnp.where(active, 1, 0)
            for i in range(1, w_len):
                acc = acc & (token_ids[:, i] == tokens_out[:, i - 1])
                n_accept = n_accept + acc.astype(jnp.int32)

            # penalty bookkeeping for accepted tokens only (spec_ok lanes
            # have no penalties, but counts must stay exact for later
            # requests reusing the lane and for stats)
            pos = jnp.arange(w_len)[None, :]
            take = (pos < n_accept[:, None]) & active[:, None]
            gen_counts = gen_counts.at[
                lane_idx[:, None], tokens_out
            ].add(take.astype(jnp.int32))
            return tokens_out, n_accept, lp_out, tkv_out, tki_out, cache, gen_counts

        kwargs = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            kwargs["out_shardings"] = (
                repl, repl, repl, repl, repl, self._cache_sharding, repl
            )
        return jax.jit(step, donate_argnums=(1, 2), **kwargs)

    def _build_extract(self):
        """Gather a sequence's KV blocks (padded to max_blocks_per_seq) for
        cross-worker transfer — the TPU-native replacement for NIXL reads
        (SURVEY.md §2.5 KV transfer plane).  Generic over the family's cache
        pytree (llama {"k","v"} symmetric; DeepSeek MLA latent + rope-key
        leaves with different widths)."""

        def fn(cache, block_ids):
            return jax.tree.map(lambda c: c[:, block_ids], cache)

        return jax.jit(fn)

    def _build_inject(self):
        """Scatter transferred KV blocks into this engine's cache, per cache
        leaf (so asymmetric-layout families inject correctly)."""
        num_blocks = self.config.num_blocks

        def fn(cache, new, block_ids, n):
            maxb = block_ids.shape[0]
            ids = jnp.where(jnp.arange(maxb) < n, block_ids, num_blocks)
            return jax.tree.map(
                lambda c, x: c.at[:, ids].set(x.astype(c.dtype), mode="drop"), cache, new
            )

        kwargs = {}
        if self.mesh is not None:
            kwargs["out_shardings"] = self._cache_sharding
        return jax.jit(fn, donate_argnums=(0,), **kwargs)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        # DYN_PROFILER_TRACE_DIR: capture a device trace of the whole serve
        # window (stopped in stop() by whichever engine started it)
        from dynamo_tpu.utils import profiling

        self._profiler_trace_dir = profiling.maybe_start_trace_from_env()
        self._stop = False
        self._thread = threading.Thread(target=self._device_loop, name="jax-engine", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self._profiler_trace_dir is not None:
            from dynamo_tpu.utils import profiling

            profiling.maybe_stop_trace()
            self._profiler_trace_dir = None
        if self.host_tier is not None:
            self.host_tier.close()  # release + delete the G3 memmap

    # -- async engine interface -------------------------------------------
    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        if request.data.get("image") is not None or request.data.get("video") is not None:
            # modality payloads are consumed by a MultimodalEngine wrapper
            # BEFORE delegation (examples/multimodal/pipeline.py); reaching
            # the text engine with one still attached means this deployment
            # has no encoder — refuse rather than silently answer from the
            # text alone
            raise ValueError(
                "this model deployment does not accept image/video input"
            )
        pre = PreprocessedRequest.from_wire(request.data)
        ctx = request.ctx
        if len(pre.token_ids) >= self.max_len:
            raise ValueError(
                f"prompt length {len(pre.token_ids)} exceeds engine max length {self.max_len}"
            )
        seq = Sequence(seq_id=ctx.id or uuid.uuid4().hex, request=pre)
        seq.trace = getattr(ctx, "trace", None)
        if pre.output_format is not None:
            seq.guided = self._make_guided_cursor(pre.output_format)
        return self._start_sequence(seq, ctx)

    def _make_guided_cursor(self, output_format: str):
        """Validate a guided request against this deployment and return a
        fresh cursor — loud 400-class errors beat silently-unconstrained
        output the client believes is schema-guaranteed."""
        if output_format not in ("json", "json_object"):
            raise ValueError(
                f"unsupported output_format {output_format!r} (want 'json')"
            )
        if self.guided_masks is None:
            raise ValueError(
                "guided JSON decoding is not enabled on this worker "
                "(engine.enable_guided_json(tokenizer) at serve time)"
            )
        if self.config.decode_steps > 1:
            # the fused scan feeds tokens back on-device; the automaton
            # advances on the host between launches, so the mask would lag
            # the generated text by up to decode_steps-1 tokens
            raise ValueError(
                "guided JSON decoding requires decode_steps=1 "
                f"(engine runs fused decode_steps={self.config.decode_steps})"
            )
        if self.spec_enabled:
            # the verify program samples the whole draft window with one
            # mask state; drafts would need per-position automaton advances
            raise ValueError(
                "guided JSON decoding does not compose with speculative "
                "decoding on this engine"
            )
        from dynamo_tpu.llm.guided import JsonCursor

        # count AFTER validation: rejected requests are not "admitted"
        self._guided_requests += 1
        return JsonCursor(
            self.guided_masks, self._guided_strings, eos_ids=self._guided_eos
        )

    def _start_sequence(self, seq: Sequence, ctx) -> ResponseStream[dict]:
        """Shared streaming tail for every entry point: wire the emit
        callback, submit to the device thread, watch for cancellation."""
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()

        def emit(tokens: list[int], finish: FinishReason | None,
                 error: str | None = None,
                 logprobs: list[float] | None = None,
                 top_logprobs: list[list[list]] | None = None) -> None:
            out = LLMEngineOutput(
                token_ids=tokens, finish_reason=finish, error=error,
                logprobs=logprobs, top_logprobs=top_logprobs,
            )
            wire = Annotated.from_data(out).to_wire(LLMEngineOutput.to_wire)
            loop.call_soon_threadsafe(out_q.put_nowait, wire)
            if finish is not None:
                loop.call_soon_threadsafe(out_q.put_nowait, None)

        seq.emit = emit
        self._submit_q.put(("add", seq))
        self._wake.set()

        cancel_task = spawn_logged(self._watch_cancel(ctx, seq))

        async def gen() -> AsyncIterator[dict]:
            try:
                while True:
                    item = await out_q.get()
                    if item is None:
                        break
                    yield item
            finally:
                cancel_task.cancel()

        return ResponseStream(gen(), ctx)

    async def generate_multimodal(
        self, request: Context[dict], embeds
    ) -> ResponseStream[dict]:
        """Generate with vision patch embeddings spliced before the text
        prompt (LLaVA-style).  ``embeds``: [n_patches, hidden] float array
        from the vision encoder's projector."""
        if self._jit_prefill_mm is None:
            raise ValueError(
                f"model family {self.config.model_family!r} has no multimodal prefill"
            )
        pre = PreprocessedRequest.from_wire(request.data)
        ctx = request.ctx
        embeds = np.asarray(embeds, np.float32)
        if embeds.ndim != 2 or embeds.shape[1] != self.config.model.hidden_size:
            raise ValueError(
                f"embeds shape {embeds.shape} != [n, {self.config.model.hidden_size}]"
            )
        if len(pre.token_ids) + len(embeds) >= self.max_len:
            raise ValueError(
                f"prompt ({len(pre.token_ids)} text + {len(embeds)} patches) "
                f"exceeds engine max length {self.max_len}"
            )
        seq = Sequence(seq_id=ctx.id or uuid.uuid4().hex, request=pre, mm_embeds=embeds)
        seq.trace = getattr(ctx, "trace", None)
        if pre.output_format is not None:
            # same contract as generate(): a guided multimodal request on a
            # deployment that cannot constrain it must fail loudly (the mm
            # prefill program already threads the mask row)
            seq.guided = self._make_guided_cursor(pre.output_format)
        return self._start_sequence(seq, ctx)

    async def _watch_cancel(self, ctx, seq: Sequence) -> None:
        await ctx.stopped()
        self._submit_q.put(("abort", seq))
        self._wake.set()

    # -- disaggregation API ------------------------------------------------
    async def prefill_extract(
        self, pre: PreprocessedRequest, *, device: bool = False,
        on_chunk=None,
    ) -> tuple[int, float, list | None, dict, int]:
        """Prefill-worker side: run prefill only, return (first_token,
        first_token_logprob, first_token_top_logprobs, blocks, n_blocks).  ``blocks`` is the cache pytree restricted to the
        sequence's blocks, e.g. llama ``{"k": [L, n, bs, kvh, d], "v": ...}``
        — host numpy by default, device arrays with ``device=True`` (the
        same-process/ICI transfer path: no host staging).

        ``on_chunk`` (streamed disagg transfer): called from the DEVICE
        thread as ``on_chunk(start_block, leaves, count)`` for each run of
        fully-written blocks after an intermediate prefill chunk, while
        later chunks still compute.  The final return then carries only the
        TAIL blocks past the streamed watermark (``n_blocks`` stays the
        sequence total).  Requires chunked prefill to fire; without it the
        call degenerates to the single-shot contract."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        seq = Sequence(
            seq_id=uuid.uuid4().hex, request=pre, prefill_only=True,
            extract_device=device,
        )
        seq.on_chunk_done = on_chunk
        if pre.output_format is not None:
            # constrain the FIRST sampled token on the prefill side so the
            # decode worker's cursor (generate_prefilled) accepts it — this
            # is what makes guided decoding compose with disaggregation
            seq.guided = self._make_guided_cursor(pre.output_format)

        def on_done(result) -> None:
            def resolve() -> None:
                if fut.done():
                    return
                if isinstance(result, BaseException):
                    fut.set_exception(result)
                else:
                    fut.set_result(result)

            loop.call_soon_threadsafe(resolve)

        seq.on_prefill_done = on_done
        self._submit_q.put(("add", seq))
        self._wake.set()
        return await fut

    def reserve_blocks(self, num_tokens: int) -> list[int] | None:
        return self.allocator.reserve_blocks(num_tokens)

    def release_blocks(self, block_ids: list[int]) -> None:
        self.allocator.release_blocks(block_ids)

    async def inject_blocks(self, block_ids: list[int], blocks: dict) -> None:
        """Decode-worker side: write transferred KV blocks (cache pytree of
        host or device arrays) into the cache (runs on the device thread to
        serialize with step functions)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def done(exc: BaseException | None = None) -> None:
            def resolve() -> None:
                if fut.done():
                    return
                if exc is not None:
                    fut.set_exception(exc)
                else:
                    fut.set_result(None)

            loop.call_soon_threadsafe(resolve)

        self._submit_q.put(("inject", (list(block_ids), blocks, done)))
        self._wake.set()
        await fut

    async def generate_prefilled(
        self, request: Context[dict], block_ids: list[int], first_token: int,
        first_token_logprob: float | None = None,
        first_token_top_logprobs: list | None = None,
    ) -> ResponseStream[dict]:
        """Decode-worker side: start decoding a sequence whose prompt KV was
        injected into ``block_ids`` and whose first token was already sampled
        by the prefill worker."""
        pre = PreprocessedRequest.from_wire(request.data)
        ctx = request.ctx
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()
        seq = Sequence(seq_id=ctx.id or uuid.uuid4().hex, request=pre, remote_prefilled=True)
        seq.trace = getattr(ctx, "trace", None)
        if pre.output_format is not None:
            # disagg split: the remote prefill worker sampled first_token —
            # advance a fresh cursor over it.  A guided-enabled prefill
            # worker (prefill_extract builds its own cursor) always hands
            # over an admissible token; an unconstrained one can hand over
            # anything, including an early EOS — refuse loudly instead of
            # silently dropping the constraint.  On refusal the caller's
            # reserved landing blocks must not leak (the sole production
            # caller, llm/disagg.py, calls this outside its try/except):
            # adopt + free returns them to the pool before raising.
            cursor = None
            try:
                cursor = self._make_guided_cursor(pre.output_format)
                cursor.advance(first_token)
                if cursor.failed or (
                    first_token in self._guided_eos and not cursor.complete
                ):
                    raise ValueError(
                        "guided JSON decoding over disaggregated prefill "
                        "needs a guided-enabled prefill worker: the "
                        "remotely sampled first token is not a valid JSON "
                        "start"
                    )
            except ValueError:
                if cursor is not None:
                    # the cursor was admitted-counted, then rejected
                    self._guided_requests -= 1
                self.allocator.adopt_sequence(seq.seq_id, block_ids)
                self.allocator.free_sequence(seq.seq_id)
                raise
            seq.guided = cursor
            if cursor.complete:
                # a single token closed the whole document (e.g. a "{}"
                # token): count it here — the transition happened outside
                # _process_token, which only sees later tokens
                self._guided_completions += 1
        seq.output_ids.append(first_token)
        self.allocator.adopt_sequence(seq.seq_id, block_ids)

        def emit(tokens: list[int], finish: FinishReason | None,
                 error: str | None = None,
                 logprobs: list[float] | None = None,
                 top_logprobs: list[list[list]] | None = None) -> None:
            wire = Annotated.from_data(
                LLMEngineOutput(
                    token_ids=tokens, finish_reason=finish, error=error,
                    logprobs=logprobs, top_logprobs=top_logprobs,
                )
            ).to_wire(LLMEngineOutput.to_wire)
            loop.call_soon_threadsafe(out_q.put_nowait, wire)
            if finish is not None:
                loop.call_soon_threadsafe(out_q.put_nowait, None)

        seq.emit = emit
        # surface the prefill worker's token as the first stream item
        finish = seq.hit_stop(first_token)
        emit(
            [first_token], finish,
            logprobs=None if first_token_logprob is None else [first_token_logprob],
            top_logprobs=(
                None if first_token_top_logprobs is None
                else [first_token_top_logprobs]
            ),
        )
        if finish is None:
            self._submit_q.put(("add", seq))
            self._wake.set()
        else:
            self.allocator.free_sequence(seq.seq_id)

        cancel_task = spawn_logged(self._watch_cancel(ctx, seq))

        async def gen() -> AsyncIterator[dict]:
            try:
                while True:
                    item = await out_q.get()
                    if item is None:
                        break
                    yield item
            finally:
                cancel_task.cancel()

        return ResponseStream(gen(), ctx)

    async def warmup(self) -> None:
        """Compile every serving program up front: one throwaway greedy
        request per prefill bucket (which also compiles the decode program
        on its first window), then a full cache flush so warmup blocks
        never pollute prefix-reuse state or router indexes.  Production
        cold-start pays compiles here instead of on the first user
        request."""
        rng = np.random.default_rng(0x5EED)
        # the prefill jit emits the first token itself, so compiling the
        # decode program needs at least one full decode window on top
        want_tokens = self.config.decode_steps + 1

        async def drive(n: int, max_toks: int) -> None:
            # distinct tokens per call: identical prompts would prefix-hit
            # and compile the continued-prefill jit instead of the target
            tokens = rng.integers(
                2, max(3, self.config.model.vocab_size - 2), size=n
            ).tolist()
            req = PreprocessedRequest(
                token_ids=tokens,
                stop=StopConditions(max_tokens=max_toks, ignore_eos=True),
                eos_token_ids=[],
            )
            req.sampling.use_greedy = True
            stream = await self.generate(Context(req.to_wire()))
            async for _ in stream:
                pass

        plans: list[tuple[int, int]] = []
        prev = 0
        for bucket in self.buckets:
            if self.chunk_tokens is not None and bucket > self.chunk_tokens:
                # chunked serving never runs full-prompt programs above the
                # chunk budget; the chunk pipeline warms below
                prev = bucket
                continue
            # prompt must land IN this bucket (> prev), preferring room for
            # a full decode window under max_len (shrink max_tokens only
            # when the bucket itself touches max_len)
            n = min(bucket, self.max_len - want_tokens)
            if n <= prev:
                n = min(bucket, self.max_len - 1)
            if n <= prev or n < 2:
                logger.debug("warmup: bucket %d unreachable under max_len", bucket)
                prev = bucket
                continue
            prev = bucket
            plans.append((n, min(want_tokens, self.max_len - n)))
        if self.chunk_tokens is not None and self.max_len > self.chunk_tokens + 1:
            # one longer prompt compiles the chunk + continued-prefill jits
            n = min(2 * self.chunk_tokens, self.max_len - want_tokens)
            if n > self.chunk_tokens:
                plans.append((n, min(want_tokens, self.max_len - n)))
        if jax.config.jax_compilation_cache_dir and self.mesh is None:
            # compile the planned programs concurrently first; the drives
            # below then hit the persistent cache instead of compiling
            # one-by-one on the device thread.  Best-effort: a compile
            # failure here must not abort warmup — the lazy drive loop
            # below still compiles whatever serving actually needs.
            loop = asyncio.get_running_loop()
            try:
                await loop.run_in_executor(
                    None, partial(self.aot_precompile, [n for n, _ in plans])
                )
            except Exception:  # noqa: BLE001
                logger.exception(
                    "aot_precompile failed during warmup; falling through "
                    "to lazy compiles"
                )
        for n, toks in plans:
            await drive(n, toks)
        if self.spec_enabled:
            # warmup's random prompts never draft, so the verify program
            # would otherwise pay its compile on the first real accepting
            # step: run it once with every lane inactive (writes all drop,
            # nothing emitted) on the device thread
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()

            def done(exc) -> None:
                def resolve() -> None:
                    if fut.done():
                        return
                    if exc is not None:
                        fut.set_exception(exc)
                    else:
                        fut.set_result(None)

                loop.call_soon_threadsafe(resolve)

            self._submit_q.put(("warm_verify", done))
            self._wake.set()
            await fut
        await self.clear_kv_blocks()

    def aot_precompile(self, prompt_lens, parallel: int = 8, on_program=None) -> int:
        """Compile the serving programs for the given prompt lengths
        CONCURRENTLY, ahead of first use.

        The device loop compiles lazily — one program per first dispatch,
        strictly serially.  Against a remote compile service (or any
        multi-core compiler) that serializes what could run in parallel:
        each program is independent.  This lowers every program the
        serving loop will need for ``prompt_lens`` with exact argument
        avals and compiles them in a thread pool (XLA releases the GIL
        during compilation).

        The compiled results reach the real dispatch path through JAX's
        persistent compilation cache — ``_ensure_compile_cache()`` points
        it at DYN_COMPILE_CACHE_DIR (default ~/.cache/dynamo_tpu/jax_cache)
        at engine init, so this only skips when the operator opted out
        (DYN_COMPILE_CACHE_DIR="") or the dir was unwritable.  An aval
        mismatch would silently compile a useless twin program, so
        tests/engine/test_aot_precompile.py asserts the real serving path
        produces ZERO new cache entries after this ran.

        Single-device engines only (the sharded path's out_shardings need
        device-committed avals; multi-chip engines keep lazy compiles).
        Returns the number of programs compiled.
        """
        if self.mesh is not None:
            return 0
        if not jax.config.jax_compilation_cache_dir:
            logger.info(
                "aot_precompile: persistent compile cache disabled "
                '(DYN_COMPILE_CACHE_DIR=""); compiles stay in-process'
            )
            return 0

        sds = jax.ShapeDtypeStruct
        cfg = self.config
        vocab = cfg.model.vocab_size
        lanes = cfg.max_batch_size
        kb = cfg.logit_bias_k
        aval = lambda t: jax.tree.map(  # noqa: E731
            lambda x: sds(x.shape, x.dtype), t
        )
        params_a, cache_a = aval(self.params), aval(self.cache)
        counts_a = sds((lanes, vocab), jnp.int32)
        i32, row_a = sds((), jnp.int32), sds((vocab,), jnp.int32)
        key_a = sds((2,), jnp.uint32)
        keys_a = sds((lanes, 2), jnp.uint32)
        cos_a, sin_a = aval(self.cos), aval(self.sin)
        grow_a = aval(self._guided_true_row)
        gtable_a = aval(self._guided_table)
        gmodes_a = sds((lanes,), jnp.int32)

        def tail(n):
            f32 = lambda: sds((n,), jnp.float32)  # noqa: E731
            return (f32(), sds((n,), jnp.int32), f32(), sds((n,), jnp.bool_),
                    f32(), f32(), f32(), sds((n, kb), jnp.int32),
                    sds((n, kb), jnp.float32))

        jobs: dict[tuple, tuple] = {}  # dedup key -> (jit_fn, avals)
        blocks_fixed = sds((self.max_blocks_per_seq,), jnp.int32)
        for n in prompt_lens:
            n = min(int(n), self.max_len - 1)
            if self.chunk_tokens is not None:
                # chunked serving runs the continued-prefill program for
                # every window; shapes depend only on (window bucket,
                # table bucket for the full prompt) — mirror _run_prefill's
                # table sizing exactly
                table_len = self.allocator.blocks_needed(
                    self._bucket_len(min(n + 1, self.max_len))
                )
                table_a = sds((table_len,), jnp.int32)
                # reachable window buckets: under concurrent prefills the
                # scheduler's _plan_chunk shrinks windows block-aligned to
                # fit the shared budget, so ANY bucket up to the largest
                # window's bucket can appear — including for prompts
                # shorter than the chunk budget (they chunk too when
                # admitted with leftover budget).  The bucket set is
                # small; compiling them all keeps the concurrent-load
                # path off the lazy device-thread compiler.
                cap = self._bucket_len(min(n, self.chunk_tokens))
                for b in (x for x in self.buckets if x <= cap):
                    jobs[("prefix", b, table_len)] = (
                        self._jit_prefill_prefix,
                        (params_a, cache_a, counts_a, counts_a, i32,
                         sds((b,), jnp.int32), table_a, table_a, i32, i32, i32,
                         row_a, row_a, i32, key_a, *tail(1), grow_a,
                         cos_a, sin_a),
                    )
            if self.chunk_tokens is None or n <= self.chunk_tokens:
                # whole-prompt program: the only path when chunking is off,
                # and still the uncontended path for prompts within the
                # chunk budget
                b = self._bucket_len(n)
                jobs[("prefill", b)] = (
                    self._jit_prefill,
                    (params_a, cache_a, counts_a, counts_a, i32,
                     sds((b,), jnp.int32), blocks_fixed, i32, i32, row_a,
                     key_a, *tail(1), grow_a, cos_a, sin_a),
                )
        tables_a = sds((lanes, self.max_blocks_per_seq), jnp.int32)
        lanes_i = sds((lanes,), jnp.int32)
        if cfg.decode_steps > 1:
            jobs[("decode",)] = (
                self._jit_decode,
                (params_a, cache_a, counts_a, counts_a, lanes_i, tables_a,
                 lanes_i, keys_a, *tail(lanes), cos_a, sin_a),
            )
        else:
            jobs[("decode",)] = (
                self._jit_decode,
                (params_a, cache_a, counts_a, counts_a, lanes_i, tables_a,
                 lanes_i, lanes_i, keys_a, *tail(lanes), gtable_a, gmodes_a,
                 cos_a, sin_a),
            )
        if self._jit_verify is not None:
            w = cfg.spec_tokens + 1
            win_a = sds((lanes, w), jnp.int32)
            jobs[("verify",)] = (
                self._jit_verify,
                (params_a, cache_a, counts_a, counts_a, win_a, tables_a,
                 lanes_i, win_a, sds((lanes,), jnp.bool_), keys_a,
                 *tail(lanes), cos_a, sin_a),
            )
        if self.unified_batch and self._jit_unified is not None:
            # unified compile buckets: every reachable token-axis bucket —
            # bounded by one chunk window plus a full complement of packed
            # decode lanes — gets its mixed program warmed with the exact
            # avals _run_unified ships (page worklist shapes included), so
            # the first mixed window after a cold start never compiles on
            # the device thread
            nseed = self._unified_seed_slots
            tb = self._unified_tb
            pallas = self.attention_impl.startswith("pallas")
            ps = self._unified_ps if pallas else 1
            if self.chunk_tokens is not None:
                ucap = self._bucket_len(
                    min(self.chunk_tokens + lanes, self.max_len)
                )
            else:
                ucap = self.buckets[-1]
            for b in (x for x in self.buckets if x <= ucap):
                if pallas and b % tb:
                    continue  # unpackable bucket: the route check skips it
                ntb = max(1, b // tb)
                tok_a = sds((b,), jnp.int32)
                jobs[("unified", b)] = (
                    self._jit_unified,
                    (params_a, cache_a, counts_a, counts_a, tok_a, lanes_i,
                     sds((b,), jnp.bool_), tables_a, lanes_i, tok_a, tok_a,
                     tok_a, sds((ntb, ps), jnp.int32),
                     sds((ntb, ps), jnp.int32), sds((ntb, ps), jnp.int32),
                     sds((ntb,), jnp.int32), lanes_i, lanes_i,
                     sds((nseed,), jnp.int32), sds((nseed, vocab), jnp.int32),
                     sds((nseed, vocab), jnp.int32), keys_a, *tail(lanes),
                     cos_a, sin_a),
                )

        import concurrent.futures as cf

        t0 = time.monotonic()

        def compile_one(item):
            name, (jit_fn, avals) = item
            t = time.monotonic()
            jit_fn.lower(*avals).compile()
            logger.info("aot_precompile: %s in %.1fs", name, time.monotonic() - t)
            if on_program is not None:
                on_program(name)

        with cf.ThreadPoolExecutor(max_workers=max(1, parallel)) as ex:
            list(ex.map(compile_one, jobs.items()))
        logger.info(
            "aot_precompile: %d programs in %.1fs", len(jobs), time.monotonic() - t0
        )
        return len(jobs)

    async def clear_kv_blocks(self) -> None:
        """Admin flush: drop published prefix-cache state (runs on the device
        thread to serialize with the allocator)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def done() -> None:
            loop.call_soon_threadsafe(lambda: fut.set_result(None) if not fut.done() else None)

        self._submit_q.put(("clear_kv", done))
        self._wake.set()
        await fut

    # -- predictive prefetch ------------------------------------------------
    def prefetch_hint(
        self, block_hashes: list[int], *, source: str = "arrival"
    ) -> bool:
        """Announce a prefix expected to be requested soon (thread-safe;
        called by the worker's PrefetchListener from the asyncio thread).
        The device loop pages the hinted blocks disk→host→HBM between
        steps.  Returns False when prefetch is disabled or there is
        nothing new to queue."""
        if self.prefetch_pager is None:
            return False
        queued = self.prefetch_pager.submit(block_hashes, source=source)
        if queued:
            self._wake.set()
        return queued

    # -- stats / events ----------------------------------------------------
    def _sink_event(self, event: KvEvent) -> None:
        if self._event_sink is not None:
            self._event_sink(event)

    def stats(self) -> dict:
        """ForwardPassMetrics (reference: lib/llm/src/kv_router/protocols.rs:43-59)."""
        out = {
            "kv_active_blocks": self.allocator.used_blocks,
            "kv_total_blocks": self.allocator.num_blocks,
            "kv_cached_blocks": self.allocator.cached_blocks,
            "gpu_cache_usage_perc": self.allocator.usage,
            "num_requests_waiting": self.scheduler.num_waiting,
            "num_requests_running": self.scheduler.num_running,
            "request_total_slots": self.config.max_batch_size,
            "iterations_total": self._iterations,
            "prefix_hits_total": self.allocator.prefix_hits_total,
            "prefix_cached_tokens_total": self.allocator.prefix_cached_tokens_total,
            "spec_drafted_tokens_total": self._spec_drafted,
            "spec_accepted_tokens_total": self._spec_accepted,
            "decode_windows_overlapped_total": self._overlap_windows,
            "decode_windows_sync_total": self._sync_windows,
            "decode_windows_unified_total": self._unified_windows,
            "admission_drains_total": self._admission_drains,
            # reason-slug → count of windows (or the engine init) that fell
            # back from the unified step; each reason also logged once
            "unified_fallbacks": dict(self._unified_fallbacks),
            # resolved ragged-kernel tunables (source: knob / tuned / default)
            "kernel_config": dict(self._kernel_config),
            # windows whose page worklist outgrew the tuned page_slots and
            # repacked at the untuned full-size grid (autotune too tight)
            "unified_ps_overflows_total": self._unified_ps_overflows,
            "decode_steps_total": self._decode_steps_total,
            "guided_requests_total": self._guided_requests,
            "guided_completions_total": self._guided_completions,
            "num_preemptions_total": self.scheduler.preemptions_total,
            **self.step_telemetry.stats(),
            # utilization accounting (observability/perf.py): rolling MFU /
            # bandwidth-utilization / goodput + cumulative token totals
            **self.utilization.stats(),
            # flight-recorder summary (ring occupancy + dump bookkeeping),
            # mirrored as dyn_flight_* worker gauges by the metrics service
            **self.flight.stats(),
        }
        # emitted count from the engine's own synchronous counter: the
        # tracker's copy updates at end-of-iteration, and a caller that just
        # consumed its stream may read stats() inside that sub-ms gap
        out["tokens_emitted_total"] = self._tokens_emitted
        # wasted-work evidence: tokens whose compute bought nothing a client
        # received (preemption recompute, rejected speculative drafts)
        spec_rejected = max(0, self._spec_drafted - self._spec_accepted)
        out["preempted_tokens_total"] = self.scheduler.preempted_tokens_total
        out["spec_rejected_tokens_total"] = spec_rejected
        out["wasted_tokens_total"] = (
            self.scheduler.preempted_tokens_total + spec_rejected
        )
        if self.host_tier is not None:
            out.update(self.host_tier.stats())
            out["offload_tiers"] = self.host_tier.tiers_snapshot()
        if self.prefetch_pager is not None:
            out.update(self.prefetch_pager.stats())
        if self.phase_stats:
            # snapshot: the device thread inserts keys concurrently
            out["phase_ms"] = {
                name: {"total_ms": round(tot * 1e3, 2), "n": n,
                       "mean_ms": round(tot / n * 1e3, 3)}
                for name, (tot, n) in list(self.phase_stats.items())
            }
        return out

    # -- device thread -----------------------------------------------------
    def _device_loop(self) -> None:
        logger.info(
            "engine loop started (max_len=%d blocks=%d bs=%d buckets=%s)",
            self.max_len, self.config.num_blocks, self.config.max_batch_size, self.buckets,
        )
        while not self._stop:
            try:
                # chaos seam: an injected step failure exercises the loop's
                # keep-alive catch below (thread survives, requests continue)
                FAULTS.check(ENGINE_STEP)
                # evictions queued by asyncio-thread mutators (disagg
                # reserve_blocks) offload here, before anything can write
                # into the evicted blocks
                self.allocator.flush_offloads()
                self._drain_submissions()
                if self.prefetch_pager is not None and self.prefetch_pager.has_work():
                    # page hinted blocks up-tier between steps: a bounded
                    # slice when serving (never stalls the batch), full
                    # throttle when idle.  Progress while idle loops again
                    # immediately — an idle engine's job is to page.
                    progress = self._run_prefetch(
                        idle=not self.scheduler.has_work()
                    )
                    if progress and not self.scheduler.has_work():
                        continue
                if not self.scheduler.has_work():
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                t_step = time.perf_counter()
                emitted_before = self._tokens_emitted
                self._step_prefill_tokens = 0
                self._step_decode_tokens = 0
                self._step_attn_ctx = 0
                self._step_weight_streams = 0.0
                decision = self.scheduler.schedule()
                if not (self.unified_batch and self._maybe_run_unified(decision)):
                    self._run_split_step(decision)
                self._iterations += 1
                step_duration_s = time.perf_counter() - t_step
                self.step_telemetry.observe_step(
                    iteration=self._iterations,
                    num_running=self.scheduler.num_running,
                    num_waiting=self.scheduler.num_waiting,
                    kv_active_blocks=self.allocator.used_blocks,
                    kv_total_blocks=self.allocator.num_blocks,
                    step_duration_s=step_duration_s,
                    prefill_tokens=self._step_prefill_tokens,
                    decode_tokens=self._step_decode_tokens,
                )
                self.utilization.observe_step(
                    duration_s=step_duration_s,
                    prefill_tokens=self._step_prefill_tokens,
                    decode_tokens=self._step_decode_tokens,
                    attn_ctx_tokens=self._step_attn_ctx,
                    weight_streams=self._step_weight_streams,
                    emitted_tokens=self._tokens_emitted - emitted_before,
                )
                if self.flight.enabled:
                    preempted = self.scheduler.preemptions_total
                    if preempted > self._flight_preemptions:
                        self.flight.record_event(
                            "preemption",
                            count=preempted - self._flight_preemptions,
                            total=preempted,
                        )
                        self._flight_preemptions = preempted
                    rates = self.utilization.rates()
                    self.flight.record_step(
                        iteration=self._iterations,
                        num_running=self.scheduler.num_running,
                        num_waiting=self.scheduler.num_waiting,
                        kv_usage=self.allocator.usage,
                        prefill_tokens=self._step_prefill_tokens,
                        decode_tokens=self._step_decode_tokens,
                        emitted_tokens=self._tokens_emitted - emitted_before,
                        step_duration_s=step_duration_s,
                        mfu=rates["mfu_perc"],
                        goodput_tok_s=rates["goodput_tokens_per_second"],
                    )
            except Exception as exc:  # noqa: BLE001 — scheduler-level bug:
                # keep the thread alive (callers would hang forever), don't
                # hot-spin
                logger.exception("engine step failed")
                if self.flight.enabled:
                    self.flight.record_event(
                        "step_error", error=f"{type(exc).__name__}: {exc}"
                    )
                    self.flight.maybe_dump("step_error")
                time.sleep(0.1)
        # shutdown with a window in flight: retire it so already-computed
        # tokens reach their streams instead of vanishing with the thread
        try:
            self._sync_pipeline()
        except Exception:  # noqa: BLE001
            logger.exception("pipeline drain at shutdown failed")

    def _run_split_step(self, decision) -> None:
        """The split prefill/decode step: one dispatch per prefill window
        plus one batched decode dispatch — the engine's historical path,
        kept whole as the unified step's fallback."""
        for seq in decision.prefills:
            if seq.status == SeqStatus.FINISHED:
                continue  # failed/aborted before this step got to it
            self._maybe_record_queue_span(seq)
            t_prefill = time.time()
            try:
                with self._xprof_span("dyn.prefill"):
                    try:
                        self._run_prefill(seq)
                    except Exception as exc:  # noqa: BLE001
                        if not self._attention_fallback(exc):
                            raise
                        self._run_prefill(seq)
            except Exception as exc:  # noqa: BLE001 — fail THIS
                # sequence (free blocks, resolve its caller) and
                # keep serving; retrying would hot-spin on
                # deterministic failures and skipping the rest of
                # the batch would leave restore plans unexecuted
                logger.exception("prefill failed for %s", seq.seq_id)
                self._record_prefill_span(seq, t_prefill, status="error")
                self._fail_sequence(seq, exc)
            else:
                self._record_prefill_span(seq, t_prefill)
        decodes = [
            s for s in self.scheduler.running if s.status == SeqStatus.RUNNING
        ]
        if decodes:
            try:
                with self._xprof_span("dyn.decode"):
                    try:
                        self._run_decode(decodes)
                    except Exception as exc:  # noqa: BLE001
                        if not self._attention_fallback(exc):
                            raise
                        # compile-class failure: the previously
                        # dispatched window (old program) already
                        # executed — retire it normally, then retry
                        # this window against the rebuilt jits
                        self._sync_pipeline()
                        self._run_decode(decodes)
            except Exception as exc:  # noqa: BLE001
                logger.exception("decode step failed")
                # a poisoned in-flight window must not feed the next
                # dispatch (and _fail_sequence is about to free the
                # failing lanes' blocks)
                self._abandon_pipeline(decodes)
                for seq in decodes:
                    if seq.status == SeqStatus.RUNNING:
                        self._fail_sequence(seq, exc)
        elif self._inflight is not None:
            # nothing decodable this iteration (every lane finished,
            # is prefilling, or was preempted) while a window is
            # still in flight: retire it so its tokens emit and
            # deferred finishes release their lanes/blocks
            self._sync_pipeline()

    # -- ragged unified-batch step ----------------------------------------
    def _unified_skip(self, reason: str, detail: str | None = None) -> None:
        """Record a unified-batch fallback under a short reason slug
        (stats() → dyn_worker_unified_fallbacks_total{reason}) and log it
        once per engine per reason — the per-step route checks run every
        scheduler iteration, so unconditional logging would spam."""
        self._unified_fallbacks[reason] = (
            self._unified_fallbacks.get(reason, 0) + 1
        )
        if self.flight.enabled:
            self.flight.record_event("unified_fallback", reason=reason)
        if reason not in self._unified_fallback_logged:
            self._unified_fallback_logged.add(reason)
            logger.info(
                "unified batch fallback [%s]: %s", reason, detail or
                "window served by the split step"
            )

    def _maybe_run_unified(self, decision) -> bool:
        """Serve this iteration as ONE ragged dispatch mixing prefill
        chunks and decode tokens.  Returns False when the step needs the
        split path (which then runs unchanged): guided lanes, multimodal or
        disagg-prefill sequences, token batches past the largest compile
        bucket, or OOM requiring the preempting synchronous machinery."""
        prefills = list(decision.prefills)
        decodes = [
            s for s in self.scheduler.running
            if s.status == SeqStatus.RUNNING and s not in prefills
        ]
        if not prefills and not decodes:
            return False  # idle / window-retire-only: split loop handles
        for seq in prefills:
            if seq.prefill_only or seq.mm_embeds is not None:
                # disagg extract / multimodal keep their routes
                self._unified_skip("disagg_or_mm")
                return False
            if seq.guided is not None:
                self._unified_skip("guided")
                return False
        for seq in decodes:
            if seq.guided is not None:
                self._unified_skip("guided")
                return False

        spans: list[tuple[Sequence, int, int]] = []
        for seq in prefills:
            n = len(seq.all_token_ids)
            start = max(seq.prefilled_tokens, seq.cached_tokens)
            end = min(seq.chunk_target, n) if (
                self.chunk_tokens is not None and seq.chunk_target
            ) else n
            if end <= start:
                # degenerate window: split path owns it
                self._unified_skip("degenerate_span")
                return False
            spans.append((seq, start, end))
        if not spans:
            # decode-only iterations keep the exact-lane decode program: the
            # unified window's bucketed token axis would pad pure decode
            # upward for nothing.  Unified earns its keep exactly when a
            # prefill span shares the window — the iterations where the
            # split path pays a second dispatch and (under overlap) an
            # admission drain.  Windows from either program chain through
            # the same feedback array, so alternating costs nothing.
            # (Deliberately uncounted: this is the designed route, not a
            # fallback.)
            return False
        # decode lanes and spans both pack DENSELY — the kernel routes per
        # row, not per block — so every token costs exactly one flat slot
        total = len(decodes) + sum(end - start for _, start, end in spans)
        if total > self.buckets[-1]:
            self._unified_skip("bucket_overflow")
            return False
        bucket = self._bucket_len(total)
        if (
            self.attention_impl.startswith("pallas")
            and bucket % self._unified_tb
        ):
            # unpackable compile bucket (odd max_len tail): the kernel grid
            # needs whole token blocks
            self._unified_skip("unpackable_bucket")
            return False
        unseeded = sum(
            1 for seq, start, _ in spans if start == seq.cached_tokens
        )
        if unseeded > self._unified_seed_slots:
            self._unified_skip("seed_overflow")
            return False

        # per-window overlap gate, same rule as _overlap_ok: top_logprobs
        # lanes ship K-wide rows that belong on the synchronous path
        overlap = self.decode_overlap and not any(
            s.request.sampling.top_logprobs > 0 for s in prefills + decodes
        )
        try:
            with self._xprof_span("dyn.unified"):
                try:
                    return self._run_unified(spans, decodes, bucket, overlap)
                except Exception as exc:  # noqa: BLE001
                    if not self._attention_fallback(exc):
                        raise
                    # compile-class kernel failure: the jits were rebuilt on
                    # the XLA path; the in-flight window (old program)
                    # already executed — retire it, then retry this window.
                    # The retire can finish sequences (a stop detected one
                    # window late) and the first attempt can have failed a
                    # restore: re-filter so the retry never dispatches a
                    # freed lane's stale metadata.
                    self._unified_skip("kernel_fallback")
                    self._sync_pipeline()
                    decodes = [
                        s for s in decodes if s.status == SeqStatus.RUNNING
                    ]
                    spans = [
                        (s, a, b) for s, a, b in spans
                        if s.status in (SeqStatus.PREFILLING, SeqStatus.RUNNING)
                    ]
                    if not spans:
                        return False  # split path serves what remains
                    return self._run_unified(spans, decodes, bucket, overlap)
        except Exception as exc:  # noqa: BLE001
            logger.exception("unified step failed")
            self._abandon_pipeline(prefills + decodes)
            for seq in prefills + decodes:
                if seq.status in (SeqStatus.PREFILLING, SeqStatus.RUNNING):
                    self._fail_sequence(seq, exc)
            return True  # the step was consumed (by failing its batch)

    def _run_unified(
        self,
        spans: list[tuple[Sequence, int, int]],
        decodes: list[Sequence],
        bucket: int,
        overlap: bool,
    ) -> bool:
        """Build the ragged batch, dispatch once, then either read back
        synchronously or put the window in flight (overlap).  A newly
        admitted sequence needs NO pipeline drain here: its prefill tokens
        come from the host while resident decode lanes keep reading the
        previous window's on-device feedback."""
        timing = self._phase_timing
        t = time.perf_counter() if timing else 0.0
        lanes = self.config.max_batch_size
        tb = self._unified_tb
        bs = self.config.block_size
        oob = self.config.num_blocks * bs
        vocab = self.config.model.vocab_size
        prev = self._inflight

        # preempted-then-readmitted prefix restores run exactly like
        # _run_prefill's, but a failed restore fails ONLY its sequence (the
        # split path's per-sequence error contract — one bad host-tier read
        # must not take down every request in the window).  The plan goes
        # back first so free_sequence can unregister the garbage landing
        # blocks and release the host pins.
        failed: list[Sequence] = []
        for seq, _, _ in spans:
            restore = self.allocator.take_restore_plan(seq.seq_id)
            if restore:
                try:
                    self._restore_blocks(restore)
                except Exception as exc:  # noqa: BLE001
                    logger.exception("prefix restore failed for %s", seq.seq_id)
                    self.allocator.put_back_restore_plan(seq.seq_id, restore)
                    self._fail_sequence(seq, exc)
                    failed.append(seq)
        if failed:
            spans = [(s, a, b) for s, a, b in spans if s not in failed]
            if not spans:
                return False  # decode-only now: the split path serves it

        # decode slot growth: overlap allocates at the DEVICE context and
        # never preempts (a lagged window may still write into a victim's
        # blocks) — on OOM the pipeline drains and the preempting split
        # path serves this iteration; sync mode drains first and preempts
        # like the plain decode path.
        slots: dict[str, int] = {}
        if overlap:
            for seq in decodes:
                dev_ctx = min(
                    seq.context_len + seq.inflight_tokens, self.max_len
                )
                slot = self.scheduler.try_slots_at(
                    seq, dev_ctx, 1, max_pos=self.max_len - 1
                )
                if slot is None:
                    self._unified_skip("slot_oom")
                    self._sync_pipeline()
                    return False
                slots[seq.seq_id] = slot
        else:
            self._sync_pipeline()
            for seq in list(decodes):
                if seq.status != SeqStatus.RUNNING:
                    continue  # preempted as a victim earlier in this loop
                slot = self.scheduler.ensure_slots(
                    seq, 1, max_pos=self.max_len - 1
                )
                if slot is None:
                    self.scheduler.preempt(seq)
                    continue
                slots[seq.seq_id] = slot
            decodes = [s for s in decodes if s.status == SeqStatus.RUNNING]
            # ensure_slots may have victimized a PREFILLING span owner
            spans = [
                (s, a, b) for s, a, b in spans
                if s.status in (SeqStatus.PREFILLING, SeqStatus.RUNNING)
            ]
            if not decodes and not spans:
                return True  # everything preempted: step consumed

        token_ids = np.zeros((bucket,), np.int32)
        token_pos = np.full((bucket,), -1, np.int32)
        token_slot = np.full((bucket,), oob, np.int32)
        token_lane = np.full((bucket,), lanes, np.int32)
        use_fb = np.zeros((bucket,), bool)
        context_lens = np.zeros((lanes,), np.int32)
        sample_rows = np.zeros((lanes,), np.int32)
        sample_gate = np.zeros((lanes,), np.int32)
        nseed = self._unified_seed_slots
        # the [nseed, vocab] seed rows only exist on windows that actually
        # admit (the rare case); steady-state windows reuse one resident
        # no-op scatter instead of re-uploading ~vocab-sized zeros
        need_seed = any(
            start == seq.cached_tokens for seq, start, _ in spans
        )
        seed_lanes = seed_prompt = seed_gen = None
        if need_seed:
            seed_lanes = np.full((nseed,), lanes, np.int32)
            seed_prompt = np.zeros((nseed, vocab), np.int32)
            seed_gen = np.zeros((nseed, vocab), np.int32)

        emit_seqs: list[Sequence] = []
        cursor = 0
        for seq in decodes:
            self._prep_decode_seq(seq)
            lane = seq.lane
            dev_ctx = min(
                seq.context_len + (seq.inflight_tokens if overlap else 0),
                self.max_len,
            )
            pos = dev_ctx - 1
            token_ids[cursor] = seq.all_token_ids[-1]
            # the host's last token lags the device while a window holding
            # this lane is in flight: read the feedback array instead
            use_fb[cursor] = overlap and seq.inflight_tokens > 0
            token_pos[cursor] = pos
            token_slot[cursor] = slots[seq.seq_id]
            token_lane[cursor] = lane
            context_lens[lane] = dev_ctx
            sample_rows[lane] = cursor
            sample_gate[lane] = 1
            emit_seqs.append(seq)
            cursor += 1  # packed decode lanes: one flat slot per lane
        si = 0
        for seq, start, end in spans:
            self._maybe_record_queue_span(seq)
            lane = seq.lane
            tokens = seq.all_token_ids
            n = len(tokens)
            span = end - start
            blocks = np.asarray(
                self.allocator.block_ids(seq.seq_id), np.int32
            )
            token_ids[cursor : cursor + span] = tokens[start:end]
            ppos = np.arange(start, end, dtype=np.int32)
            token_pos[cursor : cursor + span] = ppos
            token_slot[cursor : cursor + span] = (
                blocks[ppos // bs] * bs + ppos % bs
            )
            token_lane[cursor : cursor + span] = lane
            context_lens[lane] = end
            sample_rows[lane] = cursor + span - 1
            final = end >= n
            sample_gate[lane] = 1 if final else 0
            if start == seq.cached_tokens:
                # first window of this admission: (re)seed lane sampling
                # state exactly like the split prefill programs do
                seed_lanes[si] = lane
                seed_prompt[si] = self._count_row(seq.request.token_ids)
                seed_gen[si] = self._count_row(seq.output_ids)
                si += 1
                self._seed_lane_key(seq)
                seq.sampling_seeded = True
            if final:
                emit_seqs.append(seq)
            cursor += span

        tables = self._decode_tables(decodes + [s for s, _, _ in spans])
        # packed-lane page worklist: resolve each token block's pages on the
        # host (the kernel reads physical page ids straight from scalar
        # prefetch — no per-block lane routing).  The worklist width is the
        # engine-fixed self._unified_ps, so every window of this bucket
        # shares ONE compiled program regardless of batch composition.
        if self.attention_impl.startswith("pallas"):
            from dynamo_tpu.ops.pallas import pack_page_meta

            sw = getattr(self.config.model, "sliding_window", None)
            try:
                page_meta = pack_page_meta(
                    token_lane, token_pos, self._bt_host,
                    tb_tokens=tb, block_size=bs,
                    page_slots=self._unified_ps,
                    sliding_window=sw,
                )
            except ValueError:
                if self._unified_ps_full <= self._unified_ps:
                    raise
                # tuned page_slots too tight for this window's worklist:
                # repack at the untuned full-size rung (at most one extra
                # compiled program per bucket) instead of failing the window
                self._unified_ps_overflows += 1
                page_meta = pack_page_meta(
                    token_lane, token_pos, self._bt_host,
                    tb_tokens=tb, block_size=bs,
                    page_slots=self._unified_ps_full,
                    sliding_window=sw,
                )
        else:
            # the XLA twin routes per token off token_lane/token_pos and
            # never reads the worklist: ship minimal fixed-shape dummies
            num_tb = max(1, bucket // tb)
            page_meta = (
                np.zeros((num_tb, 1), np.int32),
                np.full((num_tb, 1), -1, np.int32),
                np.zeros((num_tb, 1), np.int32),
                np.zeros((num_tb,), np.int32),
            )
        sampling_tail = self._device_sampling_tail(emit_seqs, lanes)
        if overlap and prev is not None:
            feedback_in = prev.feedback
        else:
            if self._fb_zero is None:
                self._fb_zero = jnp.zeros((lanes,), jnp.int32)
            feedback_in = self._fb_zero
        if need_seed:
            seed_args = (
                jnp.asarray(seed_lanes), jnp.asarray(seed_prompt),
                jnp.asarray(seed_gen),
            )
        else:
            if self._seed_none is None:
                self._seed_none = (
                    jnp.full((nseed,), lanes, jnp.int32),  # OOB → drop
                    jnp.zeros((nseed, vocab), jnp.int32),
                    jnp.zeros((nseed, vocab), jnp.int32),
                )
            seed_args = self._seed_none
        if timing:
            t = self._phase("decode.schedule", t)
        args = (
            jnp.asarray(token_ids), feedback_in, jnp.asarray(use_fb),
            tables, jnp.asarray(context_lens), jnp.asarray(token_pos),
            jnp.asarray(token_slot), jnp.asarray(token_lane),
            *(jnp.asarray(a) for a in page_meta),
            jnp.asarray(sample_rows), jnp.asarray(sample_gate),
            *seed_args,
        )
        if timing:
            t = self._phase("decode.upload", t)
        tokens, lps, tkvs, tkis, self.cache, self._gen_counts, self._prompt_counts = self._jit_unified(
            self.params, self.cache, self._gen_counts, self._prompt_counts,
            *args, *sampling_tail, self.cos, self.sin,
        )
        if timing:
            t = self._phase("decode.dispatch", t)

        # host bookkeeping (device-ordered: any later program — including
        # another engine's extract over published blocks — sees the writes)
        t_prefill = time.time()
        for seq, start, end in spans:
            seq.prefilled_tokens = end
            self._step_prefill_tokens += end - start
            self._step_attn_ctx += (end * (end + 1) - start * (start + 1)) // 2
            all_tokens = seq.all_token_ids
            if end >= len(all_tokens):
                if seq.status == SeqStatus.PREFILLING:
                    seq.status = SeqStatus.RUNNING
                self.allocator.publish_stored(seq.seq_id, all_tokens)
            else:
                self.allocator.publish_stored(seq.seq_id, all_tokens[:end])
            self._record_prefill_span(seq, t_prefill)
        self._step_decode_tokens += len(decodes)
        self._step_attn_ctx += int(
            sum(context_lens[s.lane] for s in decodes)
        )
        self._step_weight_streams += 1
        self._unified_windows += 1
        if decodes:
            self._decode_steps_total += 1

        if not overlap:
            tokens_h = np.asarray(tokens)
            lps_h = np.asarray(lps)
            want_top = any(
                s.request.sampling.top_logprobs > 0 for s in emit_seqs
            )
            tkv_h = np.asarray(tkvs) if want_top else None
            tki_h = np.asarray(tkis) if want_top else None
            if timing:
                t = self._phase("decode.readback", t)
            self._sync_windows += 1
            for seq in emit_seqs:
                if seq.status != SeqStatus.RUNNING:
                    continue
                lane = seq.lane
                want = seq.request.sampling.top_logprobs > 0
                self._process_token(
                    seq, int(tokens_h[lane]), float(lps_h[lane]),
                    top=(tkv_h[lane], tki_h[lane]) if want else None,
                )
            if timing:
                self._phase("decode.post", t)
            return True

        # overlap: the window retires one iteration from now, while the
        # NEXT window (possibly carrying a fresh admission) computes
        for arr in (tokens, lps):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        for seq in emit_seqs:
            seq.inflight_tokens += 1
        if emit_seqs:
            self._inflight = _InflightWindow(
                tokens=tokens, lps=lps, feedback=tokens,
                active=emit_seqs, lane_ids=[s.lane for s in emit_seqs],
                steps=1,
            )
        else:
            # a chunk-only window samples nothing worth retiring: nothing
            # goes in flight (KV writes are device-ordered regardless)
            self._inflight = None
        if prev is not None:
            self._retire_window(prev)
        return True

    def _attention_fallback(self, exc: BaseException) -> bool:
        """If the Pallas attention kernel is active and a step failed,
        rebuild every attention-bearing jit with the portable XLA
        implementation and report True so the caller retries once.

        Mosaic rejects geometries the XLA path handles fine (e.g. "batch
        dims must be equal" on sub-tile head counts), and a remote-compile
        service can 500 transiently; either way a kernel-compile failure
        must degrade the engine, not kill every in-flight sequence.

        Only COMPILE-class failures are retried: they surface before
        execution, so donated buffers are still intact and the retry sees
        consistent state.  A post-dispatch runtime error may have consumed
        the donated cache — retrying against it would poison every
        subsequent step, so those still fail the batch."""
        if self.attention_impl != "pallas":
            return False
        msg = f"{type(exc).__name__}: {exc}".lower()
        # HBM exhaustion often mentions "during compilation" — that is a
        # capacity problem, not a kernel problem; the gather-based fallback
        # needs MORE memory, so retrying it would fail again after paying
        # a full jit rebuild
        if "resource_exhausted" in msg or "out of memory" in msg:
            return False
        compile_markers = (
            "mosaic", "interpret mode", "compile", "lowering",
            "unimplemented", "not implemented", "unsupported",
        )
        if not any(m in msg for m in compile_markers):
            return False
        logger.warning(
            "pallas attention failed (%s); falling back to XLA attention", exc
        )
        self.attention_impl = "jax"
        self._jit_prefill = self._build_prefill()
        if self._jit_prefill_prefix is not None:
            self._jit_prefill_prefix = self._build_prefill_prefix()
        if self._jit_prefill_mm is not None:
            self._jit_prefill_mm = self._build_prefill_mm()
        self._jit_decode = self._build_decode()
        if self._jit_verify is not None:
            self._jit_verify = self._build_verify()
        if self._jit_unified is not None:
            self._jit_unified = self._build_unified()
        return True

    def _xprof_span(self, name: str):
        """jax.profiler.TraceAnnotation around a hot step when
        DYN_XPROF_ANNOTATE=1, so host spans line up with xprof device
        traces; a nullcontext otherwise."""
        if not self._xprof_annotate:
            return contextlib.nullcontext()
        return jax.profiler.TraceAnnotation(name)

    def _record_prefill_span(self, seq: Sequence, start_ts: float,
                             status: str = "ok") -> None:
        """One span per prefill window (chunked prefills show every chunk).
        The window that produced the first token carries the engine-side
        TTFT (arrival → first sample)."""
        if seq.trace is None:
            return
        # intermediate chunks leave the sequence PREFILLING; the final
        # window flips it to RUNNING (or FINISHED for prefill_only)
        final = seq.status is not SeqStatus.PREFILLING
        attrs = {
            "prefilled_tokens": seq.prefilled_tokens,
            "cached_tokens": seq.cached_tokens,
        }
        # a preemption-recompute prefill is not a first-token event: TTFT
        # attaches exactly once per request, on the window that sampled the
        # first token
        if final and status == "ok" and not seq.ttft_recorded:
            seq.ttft_recorded = True
            attrs["ttft_s"] = max(0.0, time.time() - seq.arrival_ts)
        get_recorder().record(
            "engine.prefill", seq.trace, start_ts, time.time(),
            component="engine", status=status, attrs=attrs,
        )

    def _on_preempt(self, seq: Sequence) -> None:
        """Scheduler preemption hook: close the victim's decode span (the
        wait + recompute after preemption must not be billed as decode
        time) and re-arm the queue span so the re-admission wait records as
        a second engine.queue span starting at the preemption instant.
        ``arrival_ts`` is untouched — TTFT always measures from request
        arrival, even when the first token lands after a preemption."""
        self._record_decode_span(seq, status="preempted")
        if seq.trace is not None:
            seq.queue_span_recorded = False
            seq.queue_start_ts = time.time()

    def _maybe_record_queue_span(self, seq: Sequence) -> None:
        """One engine.queue span per admission: submission (or preemption
        re-queue) → first time the scheduler put the sequence on device.
        Called at prefill scheduling AND at decode start — the latter
        covers remote-prefilled sequences, which the scheduler admits
        straight to RUNNING without a local prefill pass."""
        if seq.trace is None or seq.queue_span_recorded:
            return
        seq.queue_span_recorded = True
        get_recorder().record(
            "engine.queue", seq.trace, seq.queue_start_ts or seq.arrival_ts,
            time.time(), component="engine",
            attrs={"prompt_tokens": seq.prompt_len,
                   "cached_tokens": seq.cached_tokens},
        )

    def _record_decode_span(self, seq: Sequence, status: str = "ok") -> None:
        """Close the sequence's decode span (first decode step → finish)."""
        if seq.trace is None or seq.decode_start_ts == 0.0:
            return
        get_recorder().record(
            "engine.decode", seq.trace, seq.decode_start_ts, time.time(),
            component="engine", status=status,
            attrs={"tokens_out": len(seq.output_ids)},
        )
        seq.decode_start_ts = 0.0

    def _fail_sequence(self, seq: Sequence, exc: BaseException) -> None:
        """Terminate one sequence on an engine-side error: free its
        resources and resolve its caller with the failure."""
        self._record_decode_span(seq, status="error")
        self.scheduler.finish(seq)
        if seq.on_prefill_done:
            seq.on_prefill_done(exc)
        elif seq.emit:
            seq.emit([], FinishReason.ERROR, f"{type(exc).__name__}: {exc}")

    def _drain_submissions(self) -> None:
        while True:
            try:
                op, seq = self._submit_q.get_nowait()
            except thread_queue.Empty:
                return
            if op == "add":
                # read BEFORE add: after add the new sequence itself makes
                # the scheduler busy
                backlog = self.scheduler.has_work()
                self.scheduler.add(seq)
                if (
                    self.prefetch_pager is not None
                    and self.prefix_caching
                    and seq.mm_embeds is None
                    and not seq.remote_prefilled
                    and (
                        backlog
                        or not self.allocator.can_allocate(
                            len(seq.request.token_ids)
                        )
                    )
                ):
                    # queue-hint: while this sequence waits for admission
                    # (budget/lane/blocks), its offloaded prefix pages in
                    # behind the current batch — the page-in that demand
                    # paging would have paid inside allocate_sequence.  An
                    # idle engine with room admits the sequence this same
                    # iteration, so hashing the prompt here (device
                    # thread) would be pure duplicate work — skip it.
                    hashes = compute_block_hashes(
                        seq.request.token_ids, self.config.block_size
                    )
                    if hashes:
                        self.prefetch_pager.submit(hashes, source="queued")
            elif op == "abort":
                if seq.status == SeqStatus.RUNNING:
                    # abort frees the lane's blocks: drain the decode
                    # pipeline first so no lagged in-flight step writes
                    # into storage the allocator is about to reclaim.
                    # (Only RUNNING lanes can be in a window — cancelling
                    # a still-queued request must not stall the pipeline.)
                    self._sync_pipeline()
                if seq.status != SeqStatus.FINISHED:
                    self._record_decode_span(seq, status="cancelled")
                    self.scheduler.abort(seq)
                    seq.status = SeqStatus.FINISHED
                    if seq.emit:
                        seq.emit([], FinishReason.CANCELLED)
            elif op == "warm_verify":
                done = seq  # payload: completion callback (exc | None)
                try:
                    try:
                        self._warm_verify_step()
                    except Exception as exc:  # noqa: BLE001 — same fallback
                        # contract as prefill/decode: compile-class kernel
                        # failures degrade to XLA attention and retry once
                        if not self._attention_fallback(exc):
                            raise
                        self._warm_verify_step()
                except Exception as exc:  # noqa: BLE001 — surface to the
                    # awaiting warmup() call; a swallowed failure here could
                    # hide a donation-consumed cache behind a "successful"
                    # warmup
                    logger.exception("verify warmup failed")
                    done(exc)
                else:
                    done(None)
            elif op == "clear_kv":
                done = seq  # payload is the completion callback
                # admin flush: retire the in-flight window first so deferred
                # finishes release their blocks before the count is judged
                # (warmup asserts a clean pool right after this resolves)
                self._sync_pipeline()
                cleared = self.allocator.clear_published()
                if self.host_tier is not None:
                    self.host_tier.clear()
                logger.info("cleared %d published kv block hashes", cleared)
                if done is not None:
                    done()
            elif op == "inject":
                # evictions queued by the reservation for THIS inject (or any
                # other asyncio-thread mutator) must offload before the
                # inject overwrites their blocks — the loop-top flush does
                # not cover reservations racing into the same drain pass
                self.allocator.flush_offloads()
                block_ids, blocks, done = seq  # payload tuple
                n = len(block_ids)
                nb = self._table_len(n)  # bucketed, not max-padded
                ids = np.zeros((nb,), np.int32)
                ids[:n] = block_ids
                # pad each leaf to the bucketed id length; leaf geometry
                # comes from the live cache pytree, so asymmetric layouts
                # (DeepSeek MLA latent/rope widths) shape correctly.  Device
                # arrays (same-process transfer) pad on device — no host hop
                def pad(leaf, incoming):
                    if isinstance(incoming, jax.Array):
                        if incoming.devices() <= leaf.devices():
                            out = jnp.zeros(
                                (leaf.shape[0], nb, *leaf.shape[2:]),
                                incoming.dtype,
                            )
                            return out.at[:, :n].set(incoming)
                        # same-process transfer from an engine on a
                        # DIFFERENT device partition (disagg prefill mesh →
                        # decode mesh): this engine owns placement, so hop
                        # through host and let the jit place the result on
                        # OUR devices
                        incoming = jax.device_get(incoming)
                    incoming = np.asarray(incoming)
                    out = np.zeros((leaf.shape[0], nb, *leaf.shape[2:]), incoming.dtype)
                    out[:, :n] = incoming
                    return jnp.asarray(out)

                try:
                    padded = jax.tree.map(pad, self.cache, blocks)
                    self.cache = self._jit_inject(
                        self.cache, padded, jnp.asarray(ids), jnp.int32(n)
                    )
                except Exception as exc:  # noqa: BLE001 — fail the caller,
                    # don't leave its future hanging
                    logger.exception("kv inject failed")
                    done(exc)
                else:
                    done()

    def _bucket_len(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _table_len(self, nblocks: int) -> int:
        """Smallest block-table compile bucket covering ``nblocks``.
        Batched ops (offload flush, transfer benchmarks) can exceed one
        sequence's table — those bucket to the next power of two."""
        for b in self._table_buckets:
            if b >= nblocks:
                return b
        n = self.max_blocks_per_seq
        while n < nblocks:
            n <<= 1
        return min(n, self.config.num_blocks)

    # -- G2 host offload ---------------------------------------------------
    def _offload_blocks(self, pairs: list[tuple[int, int]]) -> list[int]:
        """Allocator eviction hook: copy the evicted blocks' cache slices to
        the host tier in ONE bucketed gather + device→host transfer (device
        thread, before the new owners write).  Returns hashes that failed to
        offload (host tier full of pins) — those must be announced removed."""
        n = len(pairs)
        nb = self._table_len(n)
        ids = np.zeros((nb,), np.int32)
        for i, (bid, _) in enumerate(pairs):
            ids[i] = bid
        gathered = jax.tree.map(
            np.asarray, self._jit_extract(self.cache, jnp.asarray(ids))
        )
        failed: list[int] = []
        # host-LRU evictions triggered by these puts are judged AFTER the
        # whole batch: a hash evicted mid-batch may be re-inserted by a
        # later put (no event), or end up in no tier (removed event)
        self._host_evictions = []
        try:
            for i, (_, h) in enumerate(pairs):
                content = jax.tree.map(lambda a, i=i: a[:, i], gathered)
                if not self.host_tier.put(h, content):
                    failed.append(h)
            for h in self._host_evictions:
                if (
                    not self.host_tier.has(h)
                    and not self.allocator.is_registered(h)
                    and h not in failed
                ):
                    failed.append(h)
        finally:
            self._host_evictions = None
        return failed

    def _host_evicted(self, seq_hash: int) -> None:
        """Host-tier LRU eviction observer.  During an offload batch the
        verdict is deferred to the end of the batch (a later put may
        re-insert the hash); host puts only happen inside batches, but keep
        a direct-emit fallback for any other path."""
        if self._host_evictions is not None:
            self._host_evictions.append(seq_hash)
            return
        if not self.allocator.is_registered(seq_hash):
            self.allocator.emit_removed([seq_hash])

    # -- predictive prefetch execution (device thread) ---------------------
    def _run_prefetch(self, idle: bool) -> bool:
        """Drain the pager within this iteration's block budget.  Returns
        True when any block actually moved (the idle loop uses it to keep
        paging without sleeping; headroom-deferred work must NOT spin)."""
        pager = self.prefetch_pager
        # effective budget is link-priced: a tier behind ici/dcn gets a
        # smaller per-step allowance (all-local topology = full budget)
        budget = pager.effective_blocks_per_step() * (pager.idle_boost if idle else 1)
        progress = False
        moved = 0
        wall0 = time.time()
        t0 = time.perf_counter()
        while budget > 0:
            job = pager.next_job()
            if job is None:
                break
            touched, leftover = self._execute_prefetch(job.hashes, budget)
            budget -= max(touched, 1)  # an all-resident job still costs a walk
            moved += touched
            progress = progress or touched > 0
            if leftover:
                # HBM headroom exhausted or the block budget cut the chain:
                # retry the rest next round instead of dropping it, and
                # stop this round (further jobs would fare the same).  The
                # original enqueue time rides along so a hint that keeps
                # deferring past its TTL still goes stale.
                pager.requeue(leftover, enqueued=job.enqueued)
                break
        # hot-prefix pinning rides the prefetch loop (never the demand
        # path): promote + pin prefixes that keep paging back in
        pinned = self.host_tier.pin_hot()
        if self._phase_timing:
            self._phase("prefetch.page", t0)
        if moved:
            # prefetch work is not tied to any request: spans hang off the
            # engine-lifetime prefetch root trace (one trace id per engine)
            get_recorder().record(
                "engine.prefetch", self._prefetch_trace, wall0, time.time(),
                component="engine",
                attrs={"blocks": moved, "idle": idle, "pinned": pinned},
            )
        return progress or pinned > 0

    def _execute_prefetch(
        self, hashes: list[int], budget: int
    ) -> tuple[int, list[int]]:
        """Page one hinted prefix toward HBM: walk the hash chain, promote
        disk/remote-resident blocks into the host tier (OffloadManager
        onboard path), then pre-restore host-resident blocks into device
        landing blocks drawn from the TRULY-free list under the headroom
        reservation.  Returns (blocks touched, leftover hashes the caller
        must requeue: headroom-deferred plus any chain tail the block
        budget cut off — a long prefix finishes over later iterations
        instead of losing its tail)."""
        pager = self.prefetch_pager
        touched = 0
        restore: list[int] = []
        promote: list[int] = []
        overflow: list[int] = []
        for i, h in enumerate(hashes):
            if len(restore) >= budget:
                overflow = [
                    x for x in hashes[i:]
                    if not self.allocator.is_registered(x)
                ]
                break
            if self.allocator.is_registered(h):
                continue  # already in HBM
            tier = self.host_tier.locate(h)
            if tier is None:
                break  # chain broken: content gone — deeper blocks useless
            if tier > 0:
                promote.append(h)
            restore.append(h)
        if promote:
            moved = self.host_tier.promote_to_host(promote)
            pager.record_onboarded(moved)
            touched += moved
        if not restore:
            return touched, overflow
        plan, deferred = self.allocator.prefetch_reserve(
            restore, self._prefetch_headroom_blocks
        )
        if plan:
            t0 = time.perf_counter()
            try:
                self._restore_blocks(plan)
            except Exception:  # noqa: BLE001 — prefetch is best-effort; a
                # failed speculative restore must not poison serving
                logger.exception("prefetch restore failed")
                self.allocator.abort_prefetch(plan)
                return touched, []
            cost = (time.perf_counter() - t0) / len(plan)
            self.allocator.finish_prefetch(plan)
            for h, _bid in plan:
                pager.record_restored(h, cost)
            touched += len(plan)
        return touched, deferred + overflow

    def _restore_blocks(self, plan: list[tuple[int, int]]) -> None:
        """Scatter pinned host blocks into their device landing blocks (one
        batched inject, id array bucketed)."""
        n = len(plan)
        nb = self._table_len(n)
        ids = np.full((nb,), self.config.num_blocks, np.int32)
        staged = {
            k: np.zeros((v.shape[0], nb, *v.shape[2:]), np.dtype(v.dtype))
            for k, v in dict(self.cache).items()
        }
        # one batched read per tier (a G4-resident prefix costs one DCN
        # round trip for the whole plan, not one per block)
        contents = self.host_tier.read_pinned_many([h for h, _ in plan])
        for i, (h, bid) in enumerate(plan):
            content = contents.get(h)
            assert content is not None, "pinned host block vanished"
            ids[i] = bid
            for name, arr in content.items():
                staged[name][:, i] = arr
        self.cache = self._jit_inject(
            self.cache, jax.tree.map(jnp.asarray, staged),
            jnp.asarray(ids), jnp.int32(n),
        )
        # content is on device now: the landing blocks become matchable
        self.allocator.register_restored(plan)

    def _sampling_arrays(self, seqs: list[Sequence], lanes: int):
        vocab = self.config.model.vocab_size
        kb = self.config.logit_bias_k
        temp = np.zeros((lanes,), np.float32)
        top_k = np.zeros((lanes,), np.int32)
        top_p = np.ones((lanes,), np.float32)
        greedy = np.ones((lanes,), bool)
        pres = np.zeros((lanes,), np.float32)
        freq = np.zeros((lanes,), np.float32)
        rep = np.ones((lanes,), np.float32)
        # OpenAI logit_bias: fixed-width sparse rows, pad id = vocab (OOB
        # drop in the scatter)
        bias_ids = np.full((lanes, kb), vocab, np.int32)
        bias_vals = np.zeros((lanes, kb), np.float32)
        for i, seq in enumerate(seqs):
            s = seq.request.sampling
            lane = seq.lane if lanes > 1 else i
            temp[lane] = s.temperature if s.temperature is not None else 0.0
            top_k[lane] = s.top_k or 0
            top_p[lane] = s.top_p if s.top_p is not None else 1.0
            greedy[lane] = bool(
                s.use_greedy or s.temperature is None or s.temperature <= 0.0
            )
            pres[lane] = s.presence_penalty or 0.0
            freq[lane] = s.frequency_penalty or 0.0
            rep[lane] = s.repetition_penalty if s.repetition_penalty else 1.0
            if s.logit_bias and kb:
                # drop out-of-vocab ids BEFORE truncating so they cannot
                # displace valid biases from the bucket
                entries = sorted(
                    (
                        (int(t), float(v))
                        for t, v in s.logit_bias.items()
                        if 0 <= int(t) < vocab
                    ),
                    key=lambda e: -abs(e[1]),
                )[:kb]  # over-wide requests keep the strongest biases
                for j, (tok, val) in enumerate(entries):
                    bias_ids[lane, j] = tok
                    bias_vals[lane, j] = val
        return temp, top_k, top_p, greedy, pres, freq, rep, bias_ids, bias_vals

    def _next_rng(self) -> np.ndarray:
        return self._host_rng.integers(0, 2**32, size=2, dtype=np.uint32)

    def _count_row(self, token_ids: list[int]) -> np.ndarray:
        """Per-vocab token counts [vocab] int32 (penalty bookkeeping)."""
        vocab = self.config.model.vocab_size
        if not token_ids:
            return np.zeros((vocab,), np.int32)
        return np.bincount(
            np.asarray(token_ids, np.int64) % vocab, minlength=vocab
        ).astype(np.int32)

    def _seed_lane_state(self, seq: Sequence) -> None:
        """Initialize a lane's penalty counts + rng key for a sequence that
        skipped local prefill (disagg decode side)."""
        prompt_row = self._count_row(seq.request.token_ids)
        gen_row = self._count_row(seq.output_ids)
        lane = jnp.int32(seq.lane)
        self._prompt_counts = self._jit_set_row(self._prompt_counts, lane, jnp.asarray(prompt_row))
        self._gen_counts = self._jit_set_row(self._gen_counts, lane, jnp.asarray(gen_row))
        self._seed_lane_key(seq)
        seq.sampling_seeded = True

    def _seed_lane_key(self, seq: Sequence) -> np.ndarray:
        """Per-lane PRNG key: derived from the request seed when given
        (reproducible sampling), else from the engine stream."""
        seed = seq.request.sampling.seed
        if seed is not None:
            # same packing as jax.random.PRNGKey(seed): [hi32, lo32]
            s = int(seed) & ((1 << 64) - 1)
            row = np.array([s >> 32, s & 0xFFFFFFFF], np.uint32)
        else:
            row = self._next_rng()
        self._lane_keys[seq.lane if seq.lane >= 0 else 0] = row
        return row

    def _extract_block_range(
        self, blocks: list[int], start_b: int, end_b: int, device: bool
    ):
        """Gather cache leaves for ``blocks[start_b:end_b]`` (device thread).
        The gather table is bucketed like _jit_extract's full-sequence use so
        streamed chunks reuse the same compiled gathers."""
        count = end_b - start_b
        ids = np.zeros((self._table_len(count),), np.int32)
        ids[:count] = blocks[start_b:end_b]
        gathered = self._jit_extract(self.cache, jnp.asarray(ids))
        if device:
            return jax.tree.map(lambda x: x[:, :count], gathered)
        return jax.tree.map(lambda x: np.asarray(x)[:, :count], gathered)

    def _stream_prefill_chunk(self, seq: Sequence, blocks: list[int], end: int) -> None:
        """Streamed disagg transfer: after an intermediate chunk wrote KV up
        to token ``end``, extract the newly COMPLETED blocks (never a
        partially-written one) and hand them to ``seq.on_chunk_done`` while
        later chunks compute.  The watermark only moves forward, so a
        preemption recompute re-runs chunks without re-streaming blocks the
        receiver already injected."""
        done_b = end // self.config.block_size
        if done_b <= seq.streamed_blocks:
            return
        start_b = seq.streamed_blocks
        out = self._extract_block_range(blocks, start_b, done_b, seq.extract_device)
        seq.streamed_blocks = done_b
        try:
            seq.on_chunk_done(start_b, out, done_b - start_b)
        except Exception:  # noqa: BLE001 — a sink bug must not kill the device loop
            logger.exception("on_chunk_done failed for %s", seq.seq_id)

    def _run_prefill(self, seq: Sequence) -> None:
        tokens = seq.all_token_ids
        n = len(tokens)
        restore = self.allocator.take_restore_plan(seq.seq_id)
        if restore:
            try:
                self._restore_blocks(restore)
            except BaseException:
                # the plan must survive a failed restore: a retry (pallas
                # fallback) re-executes it, and _fail_sequence → free_sequence
                # needs it to unregister the garbage landing blocks and
                # release the host pins
                self.allocator.put_back_restore_plan(seq.seq_id, restore)
                raise
        blocks = self.allocator.block_ids(seq.seq_id)
        temp, top_k, top_p, greedy, pres, freq, rep, bias_ids, bias_vals = (
            self._sampling_arrays([seq], 1)
        )
        sampling_tail = (
            jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(greedy), jnp.asarray(pres), jnp.asarray(freq),
            jnp.asarray(rep), jnp.asarray(bias_ids), jnp.asarray(bias_vals),
        )
        key = self._seed_lane_key(seq)
        seq.sampling_seeded = True
        lane = max(seq.lane, 0)  # prefill_only sequences have no decode lane
        # nonzero only on preemption recompute (token_ids include generated)
        gen_row = self._count_row(seq.output_ids)

        # window for this call: everything past the already-written prefix
        # (cached blocks and/or completed chunks) up to the scheduler's
        # budgeted chunk target
        start = max(seq.prefilled_tokens, seq.cached_tokens)
        end = min(seq.chunk_target, n) if (
            self.chunk_tokens is not None and seq.chunk_target
        ) else n
        final = end >= n

        if seq.mm_embeds is not None:
            # multimodal: patch embeddings occupy positions [0, mm_len),
            # text tokens follow; embeddings splice in-jit
            total = seq.context_len
            bucket = self._bucket_len(total)
            tok_arr = np.zeros((bucket,), np.int32)
            text = seq.request.token_ids + seq.output_ids
            tok_arr[seq.mm_len : seq.mm_len + len(text)] = text
            emb_pad = np.zeros((bucket, self.config.model.hidden_size), np.float32)
            emb_pad[: seq.mm_len] = seq.mm_embeds
            block_ids = np.zeros((self.max_blocks_per_seq,), np.int32)
            block_ids[: len(blocks)] = blocks
            token, lp, tkv, tki, self.cache, self._gen_counts, self._prompt_counts = self._jit_prefill_mm(
                self.params, self.cache, self._gen_counts, self._prompt_counts,
                jnp.int32(lane), jnp.asarray(emb_pad), jnp.asarray(tok_arr),
                jnp.int32(seq.mm_len), jnp.asarray(block_ids), jnp.int32(total),
                jnp.asarray(gen_row), jnp.asarray(key), *sampling_tail,
                self._guided_row(seq), self.cos, self.sin,
            )
            seq.prefilled_tokens = total
            self._step_prefill_tokens += total
            self._step_attn_ctx += total * (total + 1) // 2
            self._step_weight_streams += 1
            want_top = seq.request.sampling.top_logprobs > 0
            self._process_token(
                seq, int(token), float(lp), top=(tkv, tki) if want_top else None
            )
            return
        timing = self._phase_timing
        tp = time.perf_counter() if timing else 0.0
        # the continued-prefill jit serves prefix hits AND every chunk (an
        # intermediate first chunk needs its sample gate; start_pos=0 masks
        # the prefix away entirely)
        if self._jit_prefill_prefix is not None and (start > 0 or not final):
            # continued prefill: queries attend to the resident prefix
            # blocks (none when start == 0).  The block table is
            # bucketed like token lengths so the per-layer prefix gather
            # scales with the actual context, not max_blocks_per_seq
            start_blocks = start // self.config.block_size
            tail = tokens[start:end]
            t = len(tail)
            padded = np.zeros((self._bucket_len(t),), np.int32)
            padded[:t] = tail
            table_len = self.allocator.blocks_needed(
                self._bucket_len(min(n + 1, self.max_len))
            )
            full_ids = np.zeros((table_len,), np.int32)
            full_ids[: len(blocks)] = blocks
            tail_ids = np.zeros((table_len,), np.int32)
            tail_ids[: len(blocks) - start_blocks] = blocks[start_blocks:]
            prompt_row = self._count_row(seq.request.token_ids)
            token, lp, tkv, tki, self.cache, self._gen_counts, self._prompt_counts = self._jit_prefill_prefix(
                self.params, self.cache, self._gen_counts, self._prompt_counts,
                jnp.int32(lane), jnp.asarray(padded), jnp.asarray(full_ids),
                jnp.asarray(tail_ids), jnp.int32(t), jnp.int32(start),
                jnp.int32(n), jnp.asarray(prompt_row), jnp.asarray(gen_row),
                jnp.int32(1 if final else 0), jnp.asarray(key), *sampling_tail,
                # intermediate chunks discard their sample: no constraint
                self._guided_row(seq) if final else self._guided_true_row,
                self.cos, self.sin,
            )
        else:
            padded = np.zeros((self._bucket_len(end),), np.int32)
            padded[:end] = tokens[:end]
            block_ids = np.zeros((self.max_blocks_per_seq,), np.int32)
            block_ids[: len(blocks)] = blocks
            token, lp, tkv, tki, self.cache, self._gen_counts, self._prompt_counts = self._jit_prefill(
                self.params, self.cache, self._gen_counts, self._prompt_counts,
                jnp.int32(lane), jnp.asarray(padded), jnp.asarray(block_ids),
                jnp.int32(end), jnp.int32(0), jnp.asarray(gen_row), jnp.asarray(key),
                *sampling_tail, self._guided_row(seq), self.cos, self.sin,
            )
        if timing:
            # opt-in diagnosis only: the forced scalar sync breaks chunk
            # pipelining, so production never pays it
            tp = self._phase("prefill.dispatch", tp)
            np.asarray(token)
            self._phase("prefill.readback", tp)
        seq.prefilled_tokens = end
        # utilization accounting: this window computed [start, end) — each
        # position p attends p+1 context positions (causal)
        self._step_prefill_tokens += end - start
        self._step_attn_ctx += (end * (end + 1) - start * (start + 1)) // 2
        self._step_weight_streams += 1
        if not final:
            # intermediate chunk: KV written, no token sampled; publish the
            # completed blocks so routers (and future prompts) can hit them
            self.allocator.publish_stored(seq.seq_id, tokens[:end])
            if seq.prefill_only and seq.on_chunk_done is not None:
                self._stream_prefill_chunk(seq, blocks, end)
            return
        if seq.status == SeqStatus.PREFILLING:
            seq.status = SeqStatus.RUNNING  # last chunk done → decode
        if seq.prefill_only:
            # disagg prefill worker: hand back first token + the KV blocks.
            # With streaming, earlier chunks already shipped blocks up to the
            # watermark — extract only the tail past it (the final chunk's
            # last block is never complete before now, so the tail is always
            # non-empty and the closing part always carries blocks).
            n_used = self.allocator.blocks_needed(n)
            start_b = min(seq.streamed_blocks, n_used)
            blocks_out = self._extract_block_range(
                blocks, start_b, n_used, seq.extract_device
            )
            want_top = seq.request.sampling.top_logprobs
            top_rows = None
            if want_top > 0:
                tkv_h, tki_h = np.asarray(tkv), np.asarray(tki)
                k = min(want_top, len(tki_h))
                top_rows = [[int(tki_h[i]), float(tkv_h[i])] for i in range(k)]
            result = (int(token), float(lp), top_rows, blocks_out, n_used)
            self.scheduler.finish(seq)
            if seq.on_prefill_done:
                seq.on_prefill_done(result)
            return
        if seq.mm_embeds is None:
            self.allocator.publish_stored(seq.seq_id, tokens)
        want_top = seq.request.sampling.top_logprobs > 0
        self._process_token(
            seq, int(token), float(lp), top=(tkv, tki) if want_top else None
        )

    def _ngram_draft(self, tokens: list[int]) -> list[int]:
        """Prompt-lookup drafting: find the most recent earlier occurrence
        of the sequence's final ``spec_ngram`` tokens and propose the
        continuation that followed it (up to ``spec_tokens``)."""
        g = self.config.spec_ngram
        k = self.config.spec_tokens
        if len(tokens) < g + 1:
            return []
        # bound the host-side scan: matches far behind the tail rarely help,
        # and an O(context) rescan per lane per step would grow with
        # generation length
        tokens = tokens[-4096:]
        arr = np.asarray(tokens, np.int64)
        tail = arr[-g:]
        # windows of width g ending strictly before the final position
        windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], g)
        matches = np.flatnonzero((windows == tail).all(axis=1))
        if len(matches) == 0:
            return []
        j = int(matches[-1])  # most recent prior occurrence
        draft = arr[j + g : j + g + k]
        return draft.tolist()

    def _spec_ok(self, seq: Sequence) -> bool:
        """Greedy verification is exact only for greedy, penalty-free
        sampling (logit_bias is static per-lane and stays exact)."""
        s = seq.request.sampling
        greedy = bool(s.use_greedy or s.temperature is None or s.temperature <= 0.0)
        return (
            greedy
            and not s.presence_penalty
            and not s.frequency_penalty
            and (not s.repetition_penalty or s.repetition_penalty == 1.0)
        )

    def _run_decode(self, seqs: list[Sequence]) -> None:
        if self.spec_enabled:
            # draft first: the w-wide verify program only earns its keep
            # when enough lanes drafted (non-drafting lanes pay w× the
            # logits/sampling cost for one token)
            running = [s for s in seqs if s.status == SeqStatus.RUNNING]
            drafts = {
                seq.seq_id: self._ngram_draft(seq.all_token_ids)
                for seq in running
                if self._spec_ok(seq)
            }
            n_drafting = sum(1 for d in drafts.values() if d)
            if n_drafting and n_drafting >= (
                len(running) * self.config.spec_min_fraction
            ):
                # verify consumes host-side drafts and its acceptance count
                # gates emission per lane — inherently synchronous
                self._sync_pipeline()
                return self._run_verify_decode(seqs, drafts)
        if self._overlap_ok(seqs):
            return self._run_overlap_decode(seqs)
        self._sync_pipeline()
        return self._run_plain_decode(seqs)

    def _overlap_ok(self, seqs: list[Sequence]) -> bool:
        """Overlap serves a window only when no active lane needs per-token
        host state: guided lanes advance a host automaton that must gate the
        NEXT sample (same reason guidance pins decode_steps=1), and
        top_logprobs lanes ship K-wide rows whose readback belongs on the
        synchronous path.  Mixed batches fall back whole — lane masks can't
        split one jitted window."""
        if not self.decode_overlap:
            return False
        for seq in seqs:
            if seq.status != SeqStatus.RUNNING:
                continue
            if seq.guided is not None or seq.request.sampling.top_logprobs > 0:
                return False
        return True

    def _sync_pipeline(self) -> None:
        """Retire the in-flight window (if any): host state catches up with
        the device before anything that needs it — preemption, aborts,
        verify, the synchronous decode path, batch-composition changes."""
        w = self._inflight
        if w is None:
            return
        self._inflight = None
        self._retire_window(w)

    def _abandon_pipeline(self, seqs: list[Sequence]) -> None:
        """Decode-step failure cleanup: drop the in-flight window without
        retiring it (its arrays may be poisoned) and zero the in-flight
        token accounting so a recovered loop rebuilds from host state.
        Deferred finishes attached to the dropped window still release
        their lanes/blocks — leaking them would starve a recovered engine."""
        w = self._inflight
        self._inflight = None
        if w is not None:
            # the dropped window's device program may still be EXECUTING
            # (the failure that got us here can be a later dispatch): wait
            # for it (errors swallowed — completion, not success, is what
            # gates release) so freeing the deferred sequences' blocks
            # cannot race its lagged writes into a new owner's storage
            try:
                jax.block_until_ready(w.tokens)
            except Exception:  # noqa: BLE001 — a failed program still ended
                pass
            for seq in w.deferred:
                self.scheduler.finish(seq)
            for seq in w.active:
                seq.inflight_tokens = 0
        for seq in seqs:
            seq.inflight_tokens = 0

    def _retire_window(self, w: _InflightWindow) -> None:
        """Readback + emission for one dispatched window.  Runs AFTER the
        next window was dispatched (steady state), so the device computes
        while the host blocks here — this wait is the new `decode.retire`
        phase, replacing the old synchronous `decode.readback`."""
        timing = self._phase_timing
        t = time.perf_counter() if timing else 0.0
        try:
            tokens_host = np.asarray(w.tokens)
            lps_host = np.asarray(w.lps)
            if tokens_host.ndim == 1:
                tokens_host = tokens_host[None, :]
                lps_host = lps_host[None, :]
            if timing:
                t = self._phase("decode.retire", t)
            for seq in w.active:
                seq.inflight_tokens = max(0, seq.inflight_tokens - w.steps)
            for s in range(tokens_host.shape[0]):
                for seq in w.active:
                    if seq.status != SeqStatus.RUNNING:
                        continue  # finished at an earlier step in this window
                    self._process_token(
                        seq, int(tokens_host[s, seq.lane]),
                        float(lps_host[s, seq.lane]),
                    )
        finally:
            # sequences that finished while THIS window was in flight: their
            # lagged garbage steps have now executed (or been masked), so
            # the lane and blocks go back to the pools — even when the
            # readback/emission above raised (this window is no longer
            # reachable from self._inflight, so a skipped release here
            # would leak the lane and blocks forever)
            for seq in w.deferred:
                self.scheduler.finish(seq)
        if timing:
            self._phase("decode.post", t)

    def _finish_decoded(self, seq: Sequence) -> None:
        """Finish a sequence from the decode path.  While an in-flight
        window still references its lane the release is DEFERRED: freeing
        the blocks now would let the lagged device step garbage-write into
        storage the allocator may hand to (or prefix-match for) someone
        else.  Emission already happened — only lane/block release waits."""
        w = self._inflight
        if w is not None and seq.lane in w.lane_ids:
            seq.status = SeqStatus.FINISHED
            w.deferred.append(seq)
        else:
            self.scheduler.finish(seq)

    def _prep_decode_seq(self, seq: Sequence) -> None:
        """Shared per-sequence bookkeeping at decode dispatch (every decode
        path: overlap, plain, verify): lane sampling state for sequences
        that skipped local prefill, and first-decode span/timestamping."""
        if not seq.sampling_seeded:
            # remotely-prefilled: entered decode without a local prefill
            self._seed_lane_state(seq)
        if seq.decode_start_ts == 0.0:
            # covers remote-prefilled admission (no prefill pass)
            self._maybe_record_queue_span(seq)
            seq.decode_start_ts = time.time()

    def _run_overlap_decode(self, seqs: list[Sequence]) -> None:
        timing = self._phase_timing
        t = time.perf_counter() if timing else 0.0
        lanes = self.config.max_batch_size
        steps = self.config.decode_steps
        bs = self.config.block_size
        oob = self.config.num_blocks * bs
        prev = self._inflight

        active = [s for s in seqs if s.status == SeqStatus.RUNNING]
        if prev is not None:
            # the feedback array only carries tokens for sequences that were
            # in the previous window: a NEW sequence (fresh prefill, or a
            # lane reused after a deferred release) forces a drain + host
            # rebuild.  A SHRINKING batch keeps the pipeline hot — vacated
            # lanes get context_len 0 below, which masks them to OOB slots
            # on device (the lagged lane cannot write into freed blocks).
            prev_members = set(map(id, prev.active))
            if any(id(s) not in prev_members for s in active):
                # THE admission sync point the unified step removes: a lane
                # the feedback array doesn't cover (fresh prefill, reused
                # lane) forces a drain + host rebuild here
                self._admission_drains += 1
                self._sync_pipeline()
                prev = None
                active = [s for s in active if s.status == SeqStatus.RUNNING]
        if not active:
            self._sync_pipeline()
            return

        # pre-extend every block table to cover the window at the DEVICE
        # context (host context + dispatched-unretired tokens) — the one-step
        # stop-condition lag means these positions may be written before the
        # host learns whether the lane already finished.  No preemption here:
        # a preemption would free blocks a lagged in-flight step still
        # writes; on OOM the pipeline drains and the preempting synchronous
        # path serves this iteration instead.
        slots: dict[str, int] = {}
        for seq in active:
            # clamp at max_len: a lane the host is about to LENGTH-finish can
            # have in-flight windows past the end — those steps are pure
            # garbage (truncated at retire), and unclamped they would index
            # past the block table the max_pos cap stops growing
            dev_ctx = min(seq.context_len + seq.inflight_tokens, self.max_len)
            slot = self.scheduler.try_slots_at(
                seq, dev_ctx, steps, max_pos=self.max_len - 1
            )
            if slot is None:
                self._sync_pipeline()
                return self._run_plain_decode(seqs)
            slots[seq.seq_id] = slot

        context_lens = np.zeros((lanes,), np.int32)
        slot_ids = np.full((lanes,), oob, np.int32)
        token_ids = np.zeros((lanes,), np.int32) if prev is None else None
        for seq in active:
            self._prep_decode_seq(seq)
            lane = seq.lane
            context_lens[lane] = min(
                seq.context_len + seq.inflight_tokens, self.max_len
            )
            if steps <= 1:
                slot_ids[lane] = slots[seq.seq_id]
            if token_ids is not None:
                token_ids[lane] = seq.all_token_ids[-1]
        tables = self._decode_tables(active)
        if timing:
            t = self._phase("decode.schedule", t)
        sampling_tail = self._device_sampling_tail(active, lanes)
        # token feedback: step N+1's input IS step N's on-device output —
        # the host never sees (or waits for) the tokens it dispatches
        tok_in = prev.feedback if prev is not None else jnp.asarray(token_ids)
        lens_dev = jnp.asarray(context_lens)
        if steps <= 1:
            if self._gmodes_unguided is None:
                self._gmodes_unguided = jnp.asarray(
                    np.full((lanes,), -1, np.int32)
                )
            args = (
                tok_in, tables, lens_dev, jnp.asarray(slot_ids),
                *sampling_tail, self._guided_table, self._gmodes_unguided,
            )
            if timing:
                t = self._phase("decode.upload", t)
            tokens, lps, _tkvs, _tkis, self.cache, self._gen_counts = self._jit_decode(
                self.params, self.cache, self._gen_counts, self._prompt_counts,
                *args, self.cos, self.sin,
            )
            feedback = tokens
            w_tokens, w_lps = tokens, lps
        else:
            args = (tok_in, tables, lens_dev, *sampling_tail)
            if timing:
                t = self._phase("decode.upload", t)
            w_tokens, w_lps, _tkvs, _tkis, feedback, self.cache, self._gen_counts = self._jit_decode(
                self.params, self.cache, self._gen_counts, self._prompt_counts,
                *args, self.cos, self.sin,
            )
        if timing:
            t = self._phase("decode.dispatch", t)
        # start the device→host copies now; by the time this window is
        # retired (one iteration from now) the transfer may already be done
        for arr in (w_tokens, w_lps):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        for seq in active:
            seq.inflight_tokens += steps
        self._inflight = _InflightWindow(
            tokens=w_tokens, lps=w_lps, feedback=feedback,
            active=list(active), lane_ids=[s.lane for s in active],
            steps=steps,
        )
        self._overlap_windows += 1
        self._decode_steps_total += steps
        self._step_decode_tokens += len(active) * steps
        self._step_attn_ctx += int(context_lens.sum()) * steps
        self._step_weight_streams += steps
        if prev is not None:
            self._retire_window(prev)

    def _device_sampling_tail(self, active: list[Sequence], lanes: int) -> tuple:
        """Device copies of (lane_keys, temp, top_k, top_p, greedy, pres,
        freq, rep, bias_ids, bias_vals), reused across windows while the
        host values are unchanged (see ``_tail_cache`` in __init__)."""
        host_tail = (self._lane_keys,) + self._sampling_arrays(active, lanes)
        cached = self._tail_cache
        if cached is not None and all(
            np.array_equal(a, b) for a, b in zip(cached[0], host_tail)
        ):
            return cached[1]
        sampling_tail = tuple(jnp.asarray(x) for x in host_tail)
        self._tail_cache = (
            tuple(np.copy(x) for x in host_tail), sampling_tail
        )
        return sampling_tail

    def _decode_tables(self, active: list[Sequence]):
        """Device block-table array for a decode window.  Host rows are
        persistent and rewritten ONLY for lanes whose (sequence, block list)
        changed since the last window; the device copy is reused while every
        row is clean.  Stale rows for vacated lanes are harmless: inactive
        lanes have context_len 0, so their slots mask to OOB and attention
        reads nothing."""
        dirty = self._bt_dev is None
        for seq in active:
            lane = seq.lane
            blocks = self.allocator.block_ids(seq.seq_id)
            key = self._bt_lane_key[lane]
            if key is not None and key[0] == seq.seq_id and key[1] == blocks:
                continue
            row = self._bt_host[lane]
            n = len(blocks)
            row[:n] = blocks
            row[n:] = 0
            self._bt_lane_key[lane] = (seq.seq_id, blocks)
            dirty = True
        if dirty:
            self._bt_dev = jnp.asarray(self._bt_host)
        return self._bt_dev

    def _phase(self, name: str, t0: float) -> float:
        """Accumulate wall time since ``t0`` into ``phase_stats[name]`` and
        return a fresh timestamp (phase-timing mode only)."""
        t1 = time.perf_counter()
        s = self.phase_stats.setdefault(name, [0.0, 0])
        s[0] += t1 - t0
        s[1] += 1
        return t1

    def _run_plain_decode(self, seqs: list[Sequence]) -> None:
        timing = self._phase_timing
        t = time.perf_counter() if timing else 0.0
        lanes = self.config.max_batch_size
        steps = self.config.decode_steps
        token_ids = np.zeros((lanes,), np.int32)
        context_lens = np.zeros((lanes,), np.int32)
        oob = self.config.num_blocks * self.config.block_size
        slot_ids = np.full((lanes,), oob, np.int32)

        slots: dict[str, int] = {}
        candidates: list[Sequence] = []
        for seq in list(seqs):
            if seq.status != SeqStatus.RUNNING:
                continue  # preempted as a victim earlier in this loop
            # pre-extend the block table to cover the whole decode window
            # (when steps > 1 the device re-derives per-step slots from the
            # block tables; the returned slot is then only an OOM signal)
            slot = self.scheduler.ensure_slots(seq, steps, max_pos=self.max_len - 1)
            if slot is None:
                # could not allocate even after preemption: preempt self
                self.scheduler.preempt(seq)
                continue
            slots[seq.seq_id] = slot
            candidates.append(seq)
        # build arrays only after all allocations settled: a sequence
        # preempted as a victim must not keep a live lane pointing at freed
        # (possibly re-allocated) blocks
        active = [s for s in candidates if s.status == SeqStatus.RUNNING]
        for seq in active:
            self._prep_decode_seq(seq)
            lane = seq.lane
            token_ids[lane] = seq.all_token_ids[-1]
            context_lens[lane] = seq.context_len
            if steps <= 1:
                slot_ids[lane] = slots[seq.seq_id]
        if not active:
            return
        tables = self._decode_tables(active)

        want_top = any(
            seq.request.sampling.top_logprobs > 0 for seq in active
        )
        if timing:
            t = self._phase("decode.schedule", t)
        sampling_tail = self._device_sampling_tail(active, lanes)
        if steps <= 1:
            gmodes = np.full((lanes,), -1, np.int32)
            for seq in active:
                if seq.guided is not None:
                    gmodes[seq.lane] = seq.guided.mode_id
            args = (
                jnp.asarray(token_ids), tables,
                jnp.asarray(context_lens), jnp.asarray(slot_ids),
                *sampling_tail, self._guided_table, jnp.asarray(gmodes),
            )
            if timing:
                t = self._phase("decode.upload", t)
            tokens, lps, tkvs, tkis, self.cache, self._gen_counts = self._jit_decode(
                self.params, self.cache, self._gen_counts, self._prompt_counts,
                *args, self.cos, self.sin,
            )
            if timing:
                t = self._phase("decode.dispatch", t)
            tokens_host = np.asarray(tokens)[None, :]  # [1, lanes]
            lps_host = np.asarray(lps)[None, :]
            tkv_host = np.asarray(tkvs)[None] if want_top else None
            tki_host = np.asarray(tkis)[None] if want_top else None
        else:
            args = (
                jnp.asarray(token_ids), tables,
                jnp.asarray(context_lens), *sampling_tail,
            )
            if timing:
                t = self._phase("decode.upload", t)
            tokens, lps, tkvs, tkis, _feedback, self.cache, self._gen_counts = self._jit_decode(
                self.params, self.cache, self._gen_counts, self._prompt_counts,
                *args, self.cos, self.sin,
            )
            if timing:
                t = self._phase("decode.dispatch", t)
            tokens_host = np.asarray(tokens)  # [steps, lanes]
            lps_host = np.asarray(lps)
            tkv_host = np.asarray(tkvs) if want_top else None
            tki_host = np.asarray(tkis) if want_top else None
        if timing:
            t = self._phase("decode.readback", t)
        self._sync_windows += 1
        n_steps = int(tokens_host.shape[0])
        self._decode_steps_total += n_steps
        self._step_decode_tokens += len(active) * n_steps
        self._step_attn_ctx += int(context_lens.sum()) * n_steps
        self._step_weight_streams += n_steps

        for s in range(tokens_host.shape[0]):
            for seq in active:
                if seq.status != SeqStatus.RUNNING:
                    continue  # finished at an earlier step in this window
                self._process_token(
                    seq, int(tokens_host[s, seq.lane]),
                    float(lps_host[s, seq.lane]),
                    top=(
                        (tkv_host[s, seq.lane], tki_host[s, seq.lane])
                        if want_top else None
                    ),
                )
        if timing:
            self._phase("decode.post", t)

    def _warm_verify_step(self) -> None:
        """Compile the verify program: one launch with every lane inactive
        (ctx 0 ⇒ slots OOB ⇒ all cache writes drop, nothing accepted)."""
        lanes = self.config.max_batch_size
        w = self.config.spec_tokens + 1
        oob = self.config.num_blocks * self.config.block_size
        temp, top_k, top_p, greedy, pres, freq, rep, bias_ids, bias_vals = (
            self._sampling_arrays([], lanes)
        )
        _, _, _, _, _, self.cache, self._gen_counts = self._jit_verify(
            self.params, self.cache, self._gen_counts, self._prompt_counts,
            jnp.zeros((lanes, w), jnp.int32),
            jnp.zeros((lanes, self.max_blocks_per_seq), jnp.int32),
            jnp.zeros((lanes,), jnp.int32),
            jnp.full((lanes, w), oob, jnp.int32),
            jnp.zeros((lanes,), bool), jnp.asarray(self._lane_keys),
            jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(greedy), jnp.asarray(pres), jnp.asarray(freq),
            jnp.asarray(rep), jnp.asarray(bias_ids), jnp.asarray(bias_vals),
            self.cos, self.sin,
        )

    def _run_verify_decode(self, seqs: list[Sequence], drafts: dict) -> None:
        """Speculative decode step: draft via prompt lookup, verify the
        whole window in one forward, emit the accepted prefix."""
        lanes = self.config.max_batch_size
        w = self.config.spec_tokens + 1
        bs = self.config.block_size
        oob = self.config.num_blocks * bs

        candidates: list[Sequence] = []
        for seq in list(seqs):
            if seq.status != SeqStatus.RUNNING:
                continue
            # cover the whole window (like decode_steps=w); rejected
            # positions' blocks are simply reused later
            slot = self.scheduler.ensure_slots(seq, w, max_pos=self.max_len - 1)
            if slot is None:
                self.scheduler.preempt(seq)
                continue
            candidates.append(seq)
        active = [s for s in candidates if s.status == SeqStatus.RUNNING]
        if not active:
            return

        token_mat = np.zeros((lanes, w), np.int32)
        slot_mat = np.full((lanes, w), oob, np.int32)
        block_tables = np.zeros((lanes, self.max_blocks_per_seq), np.int32)
        context_lens = np.zeros((lanes,), np.int32)
        spec_ok = np.zeros((lanes,), bool)
        for seq in active:
            self._prep_decode_seq(seq)
            lane = seq.lane
            all_tokens = seq.all_token_ids
            draft = drafts.get(seq.seq_id) or []
            if draft:
                spec_ok[lane] = True
            row = [all_tokens[-1]] + draft
            row = (row + [row[-1]] * w)[:w]  # pad: never accepted unless equal
            token_mat[lane] = row
            blocks = self.allocator.block_ids(seq.seq_id)
            block_tables[lane, : len(blocks)] = blocks
            ctx = seq.context_len
            context_lens[lane] = ctx + w - 1
            for j in range(w):
                pos = min(ctx - 1 + j, self.max_len - 1)
                slot_mat[lane, j] = blocks[pos // bs] * bs + pos % bs

        want_top = any(s.request.sampling.top_logprobs > 0 for s in active)
        sampling_tail = self._device_sampling_tail(active, lanes)
        tokens, n_accept, lps, tkvs, tkis, self.cache, self._gen_counts = self._jit_verify(
            self.params, self.cache, self._gen_counts, self._prompt_counts,
            jnp.asarray(token_mat), jnp.asarray(block_tables),
            jnp.asarray(context_lens), jnp.asarray(slot_mat),
            jnp.asarray(spec_ok), *sampling_tail,
            self.cos, self.sin,
        )
        tokens_h = np.asarray(tokens)
        n_h = np.asarray(n_accept)
        lps_h = np.asarray(lps)
        tkv_h = np.asarray(tkvs) if want_top else None
        tki_h = np.asarray(tkis) if want_top else None
        # count attempts only after the jit succeeded (an attention-fallback
        # retry re-enters this method for the same step); attempted = the
        # whole window (pads can accept too), so accepted/drafted <= 1
        self._spec_drafted += int(spec_ok.sum()) * (w - 1)
        # one verify launch streams the weights once and computes w
        # positions per active lane, EACH attending the lane's full context
        self._step_decode_tokens += len(active) * w
        self._step_attn_ctx += int(context_lens.sum()) * w
        self._step_weight_streams += 1
        for seq in active:
            lane = seq.lane
            n = int(n_h[lane])
            self._spec_accepted += max(0, n - 1)
            for i in range(n):
                if seq.status != SeqStatus.RUNNING:
                    break
                self._process_token(
                    seq, int(tokens_h[lane, i]), float(lps_h[lane, i]),
                    top=(
                        (tkv_h[lane, i], tki_h[lane, i]) if want_top else None
                    ),
                )

    def _process_token(
        self, seq: Sequence, token: int, logprob: float | None = None,
        top=None,
    ) -> None:
        seq.output_ids.append(token)
        self._tokens_emitted += 1
        if seq.guided is not None:
            was_complete = seq.guided.complete
            seq.guided.advance(token)
            if seq.guided.complete and not was_complete:
                # count the completion on the closing-token TRANSITION: a
                # document that closes exactly on the max_tokens-th token
                # (finish=LENGTH below) is still a completed document
                self._guided_completions += 1
        finish = seq.hit_stop(token)
        if finish is None and seq.guided is not None and seq.guided.complete:
            # the document just closed: stop rather than sample trailing
            # whitespace until max_tokens
            finish = FinishReason.STOP
        if finish is None and seq.context_len >= self.max_len:
            finish = FinishReason.LENGTH
        if seq.emit:
            top_rows = None
            want = seq.request.sampling.top_logprobs
            if top is not None and want > 0:
                vals, ids = top
                k = min(want, len(ids))
                top_rows = [[[int(ids[i]), float(vals[i])] for i in range(k)]]
            seq.emit(
                [token], finish,
                logprobs=None if logprob is None else [logprob],
                top_logprobs=top_rows,
            )
        if finish is not None:
            self._record_decode_span(seq)
            self._finish_decoded(seq)
        elif seq.context_len % self.config.block_size == 0 and seq.mm_embeds is None:
            # (multimodal blocks never publish: text-token hashes cannot
            # describe patch-embedding content)
            self.allocator.publish_stored(seq.seq_id, seq.all_token_ids)
