"""Embedding engine: /v1/embeddings backend.

Runs the model trunk (no LM head; models.llama.llama_forward_trunk) over the
input, masked-mean-pools the final hidden states, L2-normalizes.  Served
through the same HTTP frontend (reference: embeddings route
lib/llm/src/http/service/openai.rs:572-577).
"""

from __future__ import annotations

import asyncio
import base64
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.llm.protocols.openai import (
    EmbeddingData,
    EmbeddingRequest,
    EmbeddingResponse,
    Usage,
)
from dynamo_tpu.llm.tokenizer import HfTokenizer
from dynamo_tpu.models.llama import (
    LlamaConfig,
    init_params,
    llama_forward_trunk,
    make_rope_tables,
)


@dataclass
class EmbeddingEngineConfig:
    model: LlamaConfig
    max_length: int = 512
    seed: int = 0


class JaxEmbeddingEngine:
    def __init__(self, config: EmbeddingEngineConfig, tokenizer: HfTokenizer, params=None):
        self.config = config
        self.tokenizer = tokenizer
        cfg = config.model
        self.params = params if params is not None else init_params(cfg, jax.random.PRNGKey(config.seed))
        cos, sin = make_rope_tables(cfg)
        # slice to the served window and pass as jit args: tables built to
        # max_position_embeddings (131k for llama3) closed over as concrete
        # arrays get baked into the compiled program as tens of MB of
        # constants (same defect the serving engine fixed)
        self.cos, self.sin = cos[: config.max_length], sin[: config.max_length]

        def embed_fn(params, token_ids, seq_len, cos, sin):
            hidden = llama_forward_trunk(params, cfg, token_ids, seq_len, cos, sin)
            mask = (jnp.arange(hidden.shape[0]) < seq_len)[:, None]
            pooled = jnp.sum(hidden * mask, axis=0) / jnp.maximum(seq_len, 1)
            return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)

        self._embed = jax.jit(embed_fn)

    def _token_lists(self, request: EmbeddingRequest) -> list[list[int]]:
        """Normalize the four accepted input shapes to token-id lists."""
        inp = request.input
        if isinstance(inp, str):
            return [self.tokenizer.encode(inp)]
        if not inp:
            return []
        if isinstance(inp[0], int):
            return [list(inp)]  # a single pre-tokenized sequence
        if isinstance(inp[0], list):
            return [list(ids) for ids in inp]  # batch of pre-tokenized sequences
        return [self.tokenizer.encode(text) for text in inp]

    async def embed(self, request: EmbeddingRequest) -> EmbeddingResponse:
        if request.encoding_format not in (None, "float", "base64"):
            raise ValueError(f"unsupported encoding_format {request.encoding_format!r}")
        token_lists = self._token_lists(request)

        data = []
        total_tokens = 0
        for i, ids in enumerate(token_lists):
            ids = ids[: self.config.max_length]
            total_tokens += len(ids)
            padded = np.zeros((self.config.max_length,), np.int32)
            padded[: len(ids)] = ids
            vec = await asyncio.to_thread(
                lambda p=padded, n=len(ids): np.asarray(
                    self._embed(
                        self.params, jnp.asarray(p), jnp.int32(n),
                        self.cos, self.sin,
                    )
                )
            )
            if request.encoding_format == "base64":
                embedding: list[float] | str = base64.b64encode(
                    vec.astype(np.float32).tobytes()
                ).decode("ascii")
            else:
                embedding = [float(x) for x in vec]
            data.append(EmbeddingData(index=i, embedding=embedding))
        return EmbeddingResponse(
            model=request.model,
            data=data,
            usage=Usage(prompt_tokens=total_tokens, total_tokens=total_tokens),
        )
