"""Embedding engine: /v1/embeddings backend.

Runs the model trunk (no LM head) over the input, masked-mean-pools the
final hidden states, L2-normalizes.  Served through the same HTTP frontend
(reference: embeddings route lib/llm/src/http/service/openai.rs:572-577).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.llm.protocols.openai import (
    EmbeddingData,
    EmbeddingRequest,
    EmbeddingResponse,
    Usage,
)
from dynamo_tpu.llm.tokenizer import HfTokenizer
from dynamo_tpu.models.llama import LlamaConfig, init_params, make_rope_tables
from dynamo_tpu.ops.attention import dense_causal_attention
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rope import apply_rope


def llama_encode(params: dict, cfg: LlamaConfig, token_ids, seq_len, cos, sin):
    """Final hidden states [seq_pad, hidden] of the llama trunk."""
    s = token_ids.shape[0]
    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = jnp.arange(s, dtype=jnp.int32)

    def layer(x, w):
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        q_proj = attn_in @ w["wq"]
        k_proj = attn_in @ w["wk"]
        v_proj = attn_in @ w["wv"]
        if cfg.attention_bias:
            q_proj, k_proj, v_proj = q_proj + w["bq"], k_proj + w["bk"], v_proj + w["bv"]
        q = apply_rope(q_proj.reshape(s, cfg.num_heads, cfg.head_dim), positions, cos, sin)
        k = apply_rope(k_proj.reshape(s, cfg.num_kv_heads, cfg.head_dim), positions, cos, sin)
        v = v_proj.reshape(s, cfg.num_kv_heads, cfg.head_dim)
        attn = dense_causal_attention(q[None], k[None], v[None], seq_len[None])[0]
        x = x + attn.reshape(s, -1) @ w["wo"]
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + jax.nn.silu(mlp_in @ w["w_gate"]) * (mlp_in @ w["w_up"]) @ w["w_down"]
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


@dataclass
class EmbeddingEngineConfig:
    model: LlamaConfig
    max_length: int = 512
    seed: int = 0


class JaxEmbeddingEngine:
    def __init__(self, config: EmbeddingEngineConfig, tokenizer: HfTokenizer, params=None):
        self.config = config
        self.tokenizer = tokenizer
        cfg = config.model
        self.params = params if params is not None else init_params(cfg, jax.random.PRNGKey(config.seed))
        self.cos, self.sin = make_rope_tables(cfg)

        def embed_fn(params, token_ids, seq_len):
            hidden = llama_encode(params, cfg, token_ids, seq_len, self.cos, self.sin)
            mask = (jnp.arange(hidden.shape[0]) < seq_len)[:, None]
            pooled = jnp.sum(hidden * mask, axis=0) / jnp.maximum(seq_len, 1)
            return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)

        self._embed = jax.jit(embed_fn)

    async def embed(self, request: EmbeddingRequest) -> EmbeddingResponse:
        texts: list[str]
        if isinstance(request.input, str):
            texts = [request.input]
        elif request.input and isinstance(request.input[0], int):
            texts = [self.tokenizer.decode(list(request.input))]
        else:
            texts = list(request.input)  # type: ignore[arg-type]

        data = []
        total_tokens = 0
        for i, text in enumerate(texts):
            ids = self.tokenizer.encode(text)[: self.config.max_length]
            total_tokens += len(ids)
            padded = np.zeros((self.config.max_length,), np.int32)
            padded[: len(ids)] = ids
            vec = await asyncio.to_thread(
                lambda p=padded, n=len(ids): np.asarray(
                    self._embed(self.params, jnp.asarray(p), jnp.int32(n))
                )
            )
            data.append(EmbeddingData(index=i, embedding=[float(x) for x in vec]))
        return EmbeddingResponse(
            data=data,
            usage=Usage(prompt_tokens=total_tokens, total_tokens=total_tokens),
        )
