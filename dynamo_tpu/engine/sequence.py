"""Per-request sequence state inside the engine."""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field

from dynamo_tpu.llm.protocols.common import FinishReason, PreprocessedRequest


class SeqStatus(enum.Enum):
    WAITING = "waiting"         # queued for prefill
    PREFILLING = "prefilling"   # chunked prefill in progress (holds a lane)
    RUNNING = "running"         # decoding
    PREEMPTED = "preempted"     # evicted; will re-prefill
    FINISHED = "finished"


@dataclass
class Sequence:
    seq_id: str
    request: PreprocessedRequest
    arrival_time: float = field(default_factory=time.monotonic)
    # epoch twin of arrival_time: span timestamps are wall-clock so traces
    # from different processes line up on one timeline
    arrival_ts: float = field(default_factory=time.time)
    status: SeqStatus = SeqStatus.WAITING
    output_ids: list[int] = field(default_factory=list)
    lane: int = -1            # decode batch lane while RUNNING
    finish_reason: FinishReason | None = None
    # disaggregation modes
    prefill_only: bool = False       # prefill worker: stop after first token
    remote_prefilled: bool = False   # decode worker: KV already injected
    # prefill_only result stays as device arrays (same-process/ICI transfer)
    extract_device: bool = False
    # multimodal: projected vision patch embeddings [n_patches, hidden]
    # spliced BEFORE the text tokens at prefill (None = text-only)
    mm_embeds: object = None
    # per-lane sampling state (penalty counts, rng key) initialized?
    sampling_seeded: bool = False
    # overlapped decode: tokens dispatched in not-yet-retired windows.  The
    # device context (what the in-flight programs see) is
    # context_len + inflight_tokens; slot pre-allocation and the next
    # window's context_lens are computed there, not at the host's lagging
    # context_len.
    inflight_tokens: int = 0
    # guided decoding: host-side automaton (llm/guided.JsonCursor) whose
    # mode id selects the admissible-token mask row each step (None =
    # unconstrained)
    guided: object = None
    # prompt tokens reused from the prefix cache at allocation (the engine
    # prefills only the tail past this point)
    cached_tokens: int = 0
    # tokens whose KV is already written (cached prefix + completed chunks)
    prefilled_tokens: int = 0
    # end of the prefill window the scheduler planned for this step
    # (0 = whole prompt)
    chunk_target: int = 0
    # tracing: the request's propagated TraceContext (observability.trace);
    # engine spans (queue/prefill/decode) parent to it.  None = untraced.
    trace: object = None
    queue_span_recorded: bool = False
    ttft_recorded: bool = False   # first-token latency attached to a span
    # wall-clock start of the CURRENT queue wait (0.0 = arrival_ts; reset
    # to the preemption instant on re-queue so the second engine.queue span
    # measures only the re-admission wait, while TTFT keeps arrival_ts)
    queue_start_ts: float = 0.0
    decode_start_ts: float = 0.0  # wall-clock start of this seq's decode span
    # streamed disagg extraction (prefill_only): blocks already handed to
    # on_chunk_done.  Monotonic across preemption recompute — re-run chunks
    # below the watermark are not re-streamed (the receiver already holds
    # them; recompute is deterministic).
    streamed_blocks: int = 0
    # callbacks into the async world (set by the engine)
    emit=None                 # Callable[[Sequence, list[int], FinishReason|None], None]
    on_prefill_done=None      # Callable[[Sequence, int], None] for prefill_only
    # per-completed-chunk KV extraction callback, device thread:
    # (start_block, cache-leaves [L, count, ...], count) — None = no streaming
    on_chunk_done=None

    @property
    def mm_len(self) -> int:
        return 0 if self.mm_embeds is None else len(self.mm_embeds)

    @property
    def prompt_len(self) -> int:
        return self.mm_len + len(self.request.token_ids)

    @property
    def context_len(self) -> int:
        return self.prompt_len + len(self.output_ids)

    @property
    def all_token_ids(self) -> list[int]:
        return self.request.token_ids + self.output_ids

    def hit_stop(self, token_id: int) -> FinishReason | None:
        stop = self.request.stop
        # min_tokens suppresses EOS/stop-token finishes (not max_tokens)
        # until the minimum is generated — vLLM semantics
        min_ok = not stop.min_tokens or len(self.output_ids) >= stop.min_tokens
        if min_ok and not stop.ignore_eos and token_id in self.request.eos_token_ids:
            return FinishReason.STOP
        if min_ok and token_id in stop.stop_token_ids:
            return FinishReason.STOP
        if stop.max_tokens is not None and len(self.output_ids) >= stop.max_tokens:
            return FinishReason.LENGTH
        return None
