"""Host-side paged KV block allocator.

Manages the block pool that lives in device HBM: free list, per-sequence
block tables, and content hashes of full blocks.  Emits stored/removed KV
events (the contract the KV-aware router indexes on — reference: vLLM
KVEvents ingested via lib/llm/src/kv_router/publisher.rs; here the engine is
native so events come straight from the allocator).

Block hashing matches the router's scheme: xxh3_64 over
(parent_hash, block token ids) with seed 1337 (reference:
lib/llm/src/kv_router/indexer.rs:64,122).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from dynamo_tpu.llm.kv_router.hashing import HASH_SEED, compute_block_hashes  # noqa: F401


@dataclass
class KvEvent:
    kind: str                    # "stored" | "removed" | "cleared"
    block_hashes: list[int]
    parent_hash: int | None = None
    token_count: int = 0


@dataclass
class SequenceBlocks:
    block_ids: list[int] = field(default_factory=list)
    published_hashes: list[int] = field(default_factory=list)


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size blocks."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        *,
        event_sink: Callable[[KvEvent], None] | None = None,
        watermark: float = 0.01,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.event_sink = event_sink
        self.watermark_blocks = max(1, int(num_blocks * watermark))
        self._free: deque[int] = deque(range(num_blocks))
        self._sequences: dict[str, SequenceBlocks] = {}

    # -- capacity ----------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def usage(self) -> float:
        return self.used_blocks / self.num_blocks

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def can_allocate(self, num_tokens: int) -> bool:
        return self.free_blocks - self.blocks_needed(num_tokens) >= self.watermark_blocks

    # -- allocation --------------------------------------------------------
    def allocate_sequence(self, seq_id: str, num_tokens: int) -> list[int] | None:
        needed = self.blocks_needed(num_tokens)
        if needed > self.free_blocks:
            return None
        blocks = [self._free.popleft() for _ in range(needed)]
        self._sequences[seq_id] = SequenceBlocks(block_ids=blocks)
        return list(blocks)

    def append_slot(self, seq_id: str, context_len: int) -> int | None:
        """Slot (flat cache index) for token at position ``context_len - 1``,
        growing the block table if the token starts a new block.  None ⇒ OOM."""
        return self.append_slots(seq_id, context_len, 1)

    def append_slots(self, seq_id: str, context_len: int, steps: int,
                     max_pos: int | None = None) -> int | None:
        """Ensure the block table covers positions ``context_len - 1`` through
        ``context_len - 2 + steps`` (multi-step decode pre-allocates the whole
        window so the device can derive per-step slots from the block table).
        Returns the first position's slot, or None on OOM (nothing grown
        partially)."""
        seq = self._sequences[seq_id]
        pos = context_len - 1
        last_pos = pos + steps - 1
        if max_pos is not None:
            last_pos = min(last_pos, max_pos)
        needed = last_pos // self.block_size + 1 - len(seq.block_ids)
        if needed > len(self._free):
            return None
        for _ in range(needed):
            seq.block_ids.append(self._free.popleft())
        return seq.block_ids[pos // self.block_size] * self.block_size + pos % self.block_size

    def adopt_sequence(self, seq_id: str, block_ids: list[int]) -> None:
        """Register blocks reserved earlier (disagg: reserved before remote
        prefill, adopted when the sequence starts decoding)."""
        self._sequences[seq_id] = SequenceBlocks(block_ids=list(block_ids))

    def reserve_blocks(self, num_tokens: int) -> list[int] | None:
        """Take blocks off the free list without a sequence (disagg decode
        side reserves the landing zone for remotely-prefilled KV)."""
        needed = self.blocks_needed(num_tokens)
        if needed > self.free_blocks:
            return None
        return [self._free.popleft() for _ in range(needed)]

    def release_blocks(self, block_ids: list[int]) -> None:
        for b in block_ids:
            self._free.append(b)

    def block_ids(self, seq_id: str) -> list[int]:
        return list(self._sequences[seq_id].block_ids)

    def free_sequence(self, seq_id: str) -> None:
        seq = self._sequences.pop(seq_id, None)
        if seq is None:
            return
        for b in seq.block_ids:
            self._free.append(b)
        if seq.published_hashes and self.event_sink:
            self.event_sink(KvEvent(kind="removed", block_hashes=list(seq.published_hashes)))

    def clear_published(self) -> int:
        """Admin flush (reference: http clear_kv_blocks): forget every
        published block hash and tell routers this worker's cache is gone.
        Running sequences keep their blocks; their hashes simply re-publish
        as future blocks complete."""
        cleared = 0
        for seq in self._sequences.values():
            cleared += len(seq.published_hashes)
            seq.published_hashes = []
        if self.event_sink:
            self.event_sink(KvEvent(kind="cleared", block_hashes=[]))
        return cleared

    # -- events ------------------------------------------------------------
    def publish_stored(self, seq_id: str, token_ids: list[int]) -> None:
        """Emit stored events for newly-completed full blocks of ``seq_id``."""
        if self.event_sink is None:
            return
        seq = self._sequences.get(seq_id)
        if seq is None:
            return
        hashes = compute_block_hashes(token_ids, self.block_size)
        new = hashes[len(seq.published_hashes):]
        if not new:
            return
        parent = seq.published_hashes[-1] if seq.published_hashes else None
        seq.published_hashes = hashes
        self.event_sink(
            KvEvent(
                kind="stored",
                block_hashes=new,
                parent_hash=parent,
                token_count=len(new) * self.block_size,
            )
        )
