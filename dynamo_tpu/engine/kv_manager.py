"""Host-side paged KV block allocator with prefix-cache reuse.

Manages the block pool that lives in device HBM: free list, per-sequence
block tables, content hashes of full blocks, and a **reuse registry**:
completed blocks stay resident after their sequence finishes (refcount 0,
LRU-ordered) and incoming prompts are matched block-by-block against the
registry so a shared prefix skips prefill compute (reference: vLLM prefix
caching on the engine side + sequence-hash block reuse in
lib/llm/src/block_manager/pool.rs:447-466 ``match_sequence_hashes``).

Emits stored/removed KV events (the contract the KV-aware router indexes
on — reference: vLLM KVEvents ingested via lib/llm/src/kv_router/
publisher.rs; here the engine is native so events come straight from the
allocator).  ``stored`` fires when a block completes; ``removed`` fires when
a cached block is *evicted* (not when its sequence finishes — the content is
still resident and discoverable until then).

Block hashing matches the router's scheme: xxh3_64 over
(parent_hash, block token ids) with seed 1337 (reference:
lib/llm/src/kv_router/indexer.rs:64,122).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable

from dynamo_tpu.llm.kv_router.hashing import HASH_SEED, compute_block_hashes  # noqa: F401
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("engine.kv_manager")


@dataclass
class KvEvent:
    kind: str                    # "stored" | "removed" | "cleared"
    block_hashes: list[int]
    parent_hash: int | None = None
    token_count: int = 0


@dataclass
class SequenceBlocks:
    block_ids: list[int] = field(default_factory=list)
    published_hashes: list[int] = field(default_factory=list)
    cached_tokens: int = 0       # prefix tokens reused from the registry
    # (hash, device block) pairs whose content must be restored from the
    # host tier before this sequence prefills
    restore_plan: list[tuple[int, int]] = field(default_factory=list)


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size blocks with an
    LRU prefix-cache reuse tier.

    Block states: **free** (no content) → **in use** (refcount ≥ 1, owned by
    one or more sequences) → **cached** (refcount 0, content retained,
    evictable LRU) → free again on eviction.  Only *complete* blocks (hash
    registered via ``publish_stored``) enter the cached state.
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        *,
        event_sink: Callable[[KvEvent], None] | None = None,
        watermark: float = 0.01,
        enable_prefix_caching: bool = True,
        # G2 host tier hooks (engine/offload.py HostOffloadTier): evicted
        # registered blocks offload their content; prompt matching extends
        # into the host tier with pin-until-restore semantics
        offload_sink: Callable[[int, int], None] | None = None,
        host_tier=None,
    ):
        # predictive prefetch (prefetch/pager.py): the pager is told when a
        # prefetched block is consumed by a real sequence (hit) or leaves
        # HBM unconsumed (miss).  None = no prefetch accounting.
        self.prefetch_tracker = None
        self.num_blocks = num_blocks
        self.block_size = block_size
        # disagg's reserve/release run on the asyncio thread while the
        # device thread allocates/frees/offloads: every compound mutation
        # (capacity check + takes, refcount + registry updates) must be
        # atomic across threads.  RLock because the offload sink re-enters
        # (host-tier eviction observer calls back into the allocator).
        self._lock = threading.RLock()
        self.event_sink = event_sink
        self.enable_prefix_caching = enable_prefix_caching
        self.offload_sink = offload_sink
        self.host_tier = host_tier
        # evictions collected per public call, offloaded in ONE batched
        # device read (the new owners don't write until the engine runs its
        # step functions, strictly after the mutator returns)
        self._pending_offload: list[tuple[int, int]] = []
        self.watermark_blocks = max(1, int(num_blocks * watermark))
        self._free: deque[int] = deque(range(num_blocks))
        self._cached: OrderedDict[int, None] = OrderedDict()  # block -> None, LRU
        self._ref: dict[int, int] = {}            # block -> refcount (in-use only)
        self._block_hash: dict[int, int] = {}     # block -> registered hash
        self._hash_to_block: dict[int, int] = {}  # hash -> resident block
        self._sequences: dict[str, SequenceBlocks] = {}
        # observability
        self.prefix_cached_tokens_total = 0
        self.prefix_hits_total = 0

    # -- capacity ----------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        """Allocatable capacity: truly-free plus evictable cached blocks."""
        return len(self._free) + len(self._cached)

    @property
    def cached_blocks(self) -> int:
        return len(self._cached)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def usage(self) -> float:
        return self.used_blocks / self.num_blocks

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def can_allocate(self, num_tokens: int) -> bool:
        return self.free_blocks - self.blocks_needed(num_tokens) >= self.watermark_blocks

    # -- block lifecycle helpers ------------------------------------------
    def _take_block(self) -> int | None:
        """Pop a free block, evicting the LRU cached block if needed.  The
        evicted block's content offloads to the host tier (G2) in a batch at
        the end of the current mutator (before the new owner can write);
        hashes that fail to offload are announced ``removed``."""
        if self._free:
            return self._free.popleft()
        if self._cached:
            bid, _ = self._cached.popitem(last=False)
            h = self._block_hash.pop(bid, None)
            if h is not None and self._hash_to_block.get(h) == bid:
                del self._hash_to_block[h]
                self._pending_offload.append((bid, h))
                if self.prefetch_tracker is not None:
                    # a prefetched block leaving HBM before any sequence
                    # matched it = wasted page-in (no-op if untracked)
                    self.prefetch_tracker.on_block_evicted(h)
            return bid
        return None

    def flush_offloads(self) -> None:
        """Batched G1→G2 offload of pending evictions; any hash that is now
        resident in NO tier emits a removed event so routers forget it.
        MUST run on the device thread (the sink reads the device cache) and
        before any step function writes into the evicted blocks."""
        with self._lock:
            if not self._pending_offload:
                return
            pairs, self._pending_offload = self._pending_offload, []
            if self.offload_sink is None:
                self._emit_removed([h for _, h in pairs])
                return
            try:
                failed = list(self.offload_sink(pairs) or [])
            except Exception:  # noqa: BLE001 — eviction must proceed
                logger.exception("block offload failed; dropping %d blocks", len(pairs))
                failed = [h for _, h in pairs]
            self._emit_removed(failed)

    def _incref(self, bid: int) -> None:
        if bid in self._cached:  # cached → in use (content kept)
            del self._cached[bid]
        self._ref[bid] = self._ref.get(bid, 0) + 1

    def _decref(self, bid: int) -> None:
        ref = self._ref.get(bid, 0) - 1
        if ref > 0:
            self._ref[bid] = ref
            return
        self._ref.pop(bid, None)
        if bid in self._block_hash:
            # complete + registered: retain content for future prefix hits
            self._cached[bid] = None
        else:
            self._free.append(bid)

    def _emit_removed(self, hashes: list[int]) -> None:
        if hashes and self.event_sink:
            self.event_sink(KvEvent(kind="removed", block_hashes=hashes))

    # -- allocation --------------------------------------------------------
    def _match(
        self, token_ids: list[int] | None, *, pin_host: bool = False
    ) -> list[tuple[int, int | None]]:
        """Leading (hash, block-or-None) pairs resident in the device
        registry or the host tier (None ⇒ host hit needing a restore),
        capped so at least one prompt token is left to prefill (the model
        must still run to produce next-token logits).

        ``pin_host=True`` pins host hits against eviction until restore;
        the caller owns unpinning on rollback."""
        if not self.enable_prefix_caching or not token_ids:
            return []
        matched: list[tuple[int, int | None]] = []
        for h in compute_block_hashes(token_ids, self.block_size):
            bid = self._hash_to_block.get(h)
            if bid is None and self.host_tier is not None:
                if pin_host:
                    if not self.host_tier.pin(h):
                        break
                elif not self.host_tier.has(h):
                    break
            elif bid is None:
                break
            matched.append((h, bid))
        while matched and len(matched) * self.block_size >= len(token_ids):
            h, bid = matched.pop()
            if bid is None and pin_host:
                self.host_tier.unpin(h)
        return matched

    def match_prefix(self, token_ids: list[int]) -> int:
        """Number of prompt tokens resident across device + host tiers."""
        with self._lock:
            return len(self._match(token_ids)) * self.block_size

    def allocate_sequence(
        self, seq_id: str, num_tokens: int, token_ids: list[int] | None = None
    ) -> tuple[list[int], int] | None:
        """Allocate the block table for a new sequence of ``num_tokens``
        positions.  When ``token_ids`` (the known prompt) is given, leading
        complete blocks already resident are *shared* instead of allocated:
        returns (block_ids, cached_tokens) where the first
        ``cached_tokens // block_size`` entries are reused blocks the caller
        must not write.  None ⇒ OOM (nothing claimed)."""
        with self._lock:
            matched = self._match(token_ids, pin_host=True)
            device_hits = [(h, bid) for h, bid in matched if bid is not None]
            host_hits = [h for h, bid in matched if bid is None]
            # host hits need a fresh device block each (restored before prefill)
            needed = self.blocks_needed(num_tokens) - len(device_hits)
            # claim matched device blocks FIRST (removes them from the evictable
            # set), then check capacity against what is genuinely left — a
            # matched block in the cached LRU must not be counted as allocatable
            for _, bid in device_hits:
                self._incref(bid)
            if needed > self.free_blocks:
                for _, bid in device_hits:  # roll back: nothing claimed on OOM
                    self._decref(bid)
                for h in host_hits:
                    self.host_tier.unpin(h)
                return None
            fresh: list[int] = []
            for _ in range(max(needed, 0)):
                bid = self._take_block()
                assert bid is not None  # guaranteed by the capacity check
                self._ref[bid] = 1
                fresh.append(bid)
            self.flush_offloads()
            # matched blocks keep prompt order (device and host hits can
            # interleave); host hits take fresh blocks as restore landing zones.
            # Landing blocks are NOT registered here: registration happens in
            # ``register_restored`` after the content actually arrives, so a
            # co-scheduled prompt can never device-match a block that a failed
            # restore would leave garbage (it host-matches and restores its own
            # copy instead).
            restore_plan: list[tuple[int, int]] = []
            block_ids: list[int] = []
            fresh_iter = iter(fresh)
            for h, bid in matched:
                if bid is None:
                    bid = next(fresh_iter)
                    restore_plan.append((h, bid))
                block_ids.append(bid)
            block_ids.extend(fresh_iter)
            cached_tokens = len(matched) * self.block_size
            self._sequences[seq_id] = SequenceBlocks(
                block_ids=block_ids,
                published_hashes=[h for h, _ in matched],
                cached_tokens=cached_tokens,
                restore_plan=restore_plan,
            )
            if cached_tokens:
                self.prefix_hits_total += 1
                self.prefix_cached_tokens_total += cached_tokens
            if self.prefetch_tracker is not None:
                # prefetched blocks consumed by a real sequence: their
                # page-in cost was hidden off this request's critical path
                for h, _bid in device_hits:
                    self.prefetch_tracker.on_block_hit(h)
            return block_ids[:], cached_tokens

    def append_slot(self, seq_id: str, context_len: int) -> int | None:
        """Slot (flat cache index) for token at position ``context_len - 1``,
        growing the block table if the token starts a new block.  None ⇒ OOM."""
        return self.append_slots(seq_id, context_len, 1)

    def append_slots(self, seq_id: str, context_len: int, steps: int,
                     max_pos: int | None = None) -> int | None:
        """Ensure the block table covers positions ``context_len - 1`` through
        ``context_len - 2 + steps`` (multi-step decode pre-allocates the whole
        window so the device can derive per-step slots from the block table).
        Returns the first position's slot, or None on OOM (nothing grown
        partially)."""
        with self._lock:
            seq = self._sequences[seq_id]
            pos = context_len - 1
            last_pos = pos + steps - 1
            if max_pos is not None:
                last_pos = min(last_pos, max_pos)
            needed = last_pos // self.block_size + 1 - len(seq.block_ids)
            if needed > self.free_blocks:
                return None
            for _ in range(needed):
                bid = self._take_block()
                assert bid is not None
                self._ref[bid] = 1
                seq.block_ids.append(bid)
            self.flush_offloads()
            return seq.block_ids[pos // self.block_size] * self.block_size + pos % self.block_size

    def adopt_sequence(self, seq_id: str, block_ids: list[int]) -> None:
        """Register blocks reserved earlier (disagg: reserved before remote
        prefill, adopted when the sequence starts decoding)."""
        with self._lock:
            self._sequences[seq_id] = SequenceBlocks(block_ids=list(block_ids))

    def reserve_blocks(self, num_tokens: int) -> list[int] | None:
        """Take blocks off the free list without a sequence (disagg decode
        side reserves the landing zone for remotely-prefilled KV).

        Called from the asyncio thread — evictions are NOT flushed here
        (the offload copy reads the device cache, which only the device
        thread may touch); the engine loop flushes them before any write."""
        with self._lock:
            needed = self.blocks_needed(num_tokens)
            if needed > self.free_blocks:
                return None
            out = []
            for _ in range(needed):
                bid = self._take_block()
                assert bid is not None
                self._ref[bid] = 1
                out.append(bid)
            return out

    def release_blocks(self, block_ids: list[int]) -> None:
        with self._lock:
            for b in block_ids:
                self._decref(b)

    def block_ids(self, seq_id: str) -> list[int]:
        with self._lock:
            return list(self._sequences[seq_id].block_ids)

    def cached_tokens(self, seq_id: str) -> int:
        with self._lock:
            seq = self._sequences.get(seq_id)
            return seq.cached_tokens if seq else 0

    def is_registered(self, seq_hash: int) -> bool:
        """Whether a block with this content hash is resident on device."""
        with self._lock:
            return seq_hash in self._hash_to_block

    def emit_removed(self, hashes: list[int]) -> None:
        """Tell routers these hashes left every tier (offload-tier eviction
        with no device copy)."""
        self._emit_removed(hashes)

    def register_restored(self, plan: list[tuple[int, int]]) -> None:
        """The engine restored these (hash, landing block) pairs from the
        host tier: the blocks now hold real content and may serve device
        prefix hits.  First writer wins on duplicate hashes (two sequences
        restoring the same prefix each keep a private, unshared copy)."""
        with self._lock:
            for h, bid in plan:
                if h not in self._hash_to_block and bid not in self._block_hash:
                    self._hash_to_block[h] = bid
                    self._block_hash[bid] = h

    # -- predictive prefetch ----------------------------------------------
    def prefetch_reserve(
        self, seq_hashes: list[int], headroom_blocks: int
    ) -> tuple[list[tuple[int, int]], list[int]]:
        """Claim landing blocks for a speculative host→HBM prefetch.

        Returns ``(plan, deferred)``: ``plan`` is (hash, landing block)
        pairs with the host copies pinned (execute with the same restore
        machinery as demand paging), ``deferred`` the hashes that could
        not be served *because of the headroom reservation* — the caller
        requeues those.  Hashes already device-resident or absent from
        every offload tier are silently dropped (nothing to page).

        A prefetched block ends CACHED (refcount 0, evictable), so paging
        it in never shrinks allocatable capacity (free + cached) — the
        landing block comes from the free list or by evicting the LRU
        *cached* block (which offloads, exactly like demand eviction), and
        becomes another cached block.  Running sequences are untouchable
        (refcount ≥ 1), so prefetch can never cause a preemption.  The
        ``headroom_blocks`` floor additionally keeps prefetch from
        churning evictions when capacity is nearly exhausted: below it,
        hashes come back as deferred for a later retry."""
        plan: list[tuple[int, int]] = []
        deferred: list[int] = []
        with self._lock:
            for h in seq_hashes:
                if h in self._hash_to_block:
                    continue
                if self.free_blocks <= headroom_blocks:
                    deferred.append(h)
                    continue
                if self.host_tier is None or not self.host_tier.pin(h):
                    continue  # left every tier since the hint was made
                bid = self._take_block()
                if bid is None:
                    self.host_tier.unpin(h)
                    deferred.append(h)
                    continue
                self._ref[bid] = 1
                plan.append((h, bid))
            # evictions this reservation caused must offload before the
            # restore injects into the reclaimed blocks (device thread)
            self.flush_offloads()
        return plan, deferred

    def finish_prefetch(self, plan: list[tuple[int, int]]) -> None:
        """The engine restored + registered the plan (register_restored):
        release the landing blocks into the cached LRU, where the next
        matching prompt claims them as ordinary device prefix hits."""
        with self._lock:
            for _h, bid in plan:
                self._decref(bid)

    def abort_prefetch(self, plan: list[tuple[int, int]]) -> None:
        """A prefetch restore failed mid-flight: unregister any landing
        block that made it into the registry (its content is suspect) and
        free the blocks.  Host pins are NOT released here: the restore's
        ``read_pinned_many`` already released the pin of every hash it
        consumed, and a second release would steal a ref the tier still
        needs (e.g. a hot-prefix pin).  A failure before the read consumed
        a hash leaks that one transient pin — strictly better than
        corrupting refcounts on the far more common post-read failures."""
        with self._lock:
            for h, bid in plan:
                if self._hash_to_block.get(h) == bid:
                    del self._hash_to_block[h]
                self._block_hash.pop(bid, None)
                self._decref(bid)

    def put_back_restore_plan(self, seq_id: str, plan: list[tuple[int, int]]) -> None:
        """Re-arm a taken restore plan after a failed restore so a retry
        re-executes it and sequence teardown cleans up the landing blocks."""
        with self._lock:
            seq = self._sequences.get(seq_id)
            if seq is not None:
                seq.restore_plan = plan + seq.restore_plan

    def take_restore_plan(self, seq_id: str) -> list[tuple[int, int]]:
        """Hand the engine the pending host→device restores for a sequence
        (cleared so aborts after restore don't double-handle)."""
        with self._lock:
            seq = self._sequences.get(seq_id)
            if seq is None:
                return []
            plan, seq.restore_plan = seq.restore_plan, []
            return plan

    def free_sequence(self, seq_id: str) -> None:
        """Sequence finished: decref its blocks.  Registered (complete)
        blocks whose refcount hits zero stay resident in the LRU cache for
        future prefix hits; ``removed`` events fire only on eviction."""
        with self._lock:
            seq = self._sequences.pop(seq_id, None)
            if seq is None:
                return
            for h, bid in seq.restore_plan:
                # aborted before its restore ran: the landing block holds no
                # content — unregister it and release the host pin
                if self._hash_to_block.get(h) == bid:
                    del self._hash_to_block[h]
                self._block_hash.pop(bid, None)
                if self.host_tier is not None:
                    self.host_tier.unpin(h)
            seq.restore_plan = []
            if not self.enable_prefix_caching and seq.published_hashes:
                # without the reuse registry the content is gone the moment
                # the blocks free — routers must forget the stored hashes
                # now (with reuse, removal fires on LRU eviction instead)
                self._emit_removed(seq.published_hashes)
            for b in seq.block_ids:
                self._decref(b)

    def clear_published(self) -> int:
        """Admin flush (reference: http clear_kv_blocks): drop the whole
        reuse registry — cached blocks are freed, in-use registered blocks
        unregister — and tell routers this worker's cache is gone.  Running
        sequences keep their blocks; their hashes simply re-publish as
        future blocks complete."""
        with self._lock:
            forgotten = set(self._hash_to_block)
            if self.prefetch_tracker is not None:
                for h in forgotten:
                    self.prefetch_tracker.on_block_evicted(h)
            for seq in self._sequences.values():
                forgotten.update(seq.published_hashes)
                seq.published_hashes = []
            cleared = len(forgotten)
            self._hash_to_block.clear()
            self._block_hash.clear()
            while self._cached:
                bid, _ = self._cached.popitem(last=False)
                self._free.append(bid)
            if self.event_sink:
                self.event_sink(KvEvent(kind="cleared", block_hashes=[]))
            return cleared

    # -- events ------------------------------------------------------------
    def publish_stored(self, seq_id: str, token_ids: list[int]) -> None:
        """Emit stored events for newly-completed full blocks of ``seq_id``
        and register them for prefix reuse."""
        with self._lock:
            seq = self._sequences.get(seq_id)
            if seq is None:
                return
            hashes = compute_block_hashes(token_ids, self.block_size)
            new = hashes[len(seq.published_hashes):]
            if not new:
                return
            parent = seq.published_hashes[-1] if seq.published_hashes else None
            if self.enable_prefix_caching:
                for idx in range(len(seq.published_hashes), len(hashes)):
                    if idx >= len(seq.block_ids):
                        break
                    h, bid = hashes[idx], seq.block_ids[idx]
                    # first writer wins: a hash already resident elsewhere keeps
                    # its mapping; this block simply stays unregistered
                    if h not in self._hash_to_block and bid not in self._block_hash:
                        self._hash_to_block[h] = bid
                        self._block_hash[bid] = h
            seq.published_hashes = hashes
            if self.event_sink:
                self.event_sink(
                    KvEvent(
                        kind="stored",
                        block_hashes=new,
                        parent_hash=parent,
                        token_count=len(new) * self.block_size,
                    )
                )
