"""Host-side paged KV block allocator with prefix-cache reuse.

Manages the block pool that lives in device HBM: free list, per-sequence
block tables, content hashes of full blocks, and a **reuse registry**:
completed blocks stay resident after their sequence finishes (refcount 0,
LRU-ordered) and incoming prompts are matched block-by-block against the
registry so a shared prefix skips prefill compute (reference: vLLM prefix
caching on the engine side + sequence-hash block reuse in
lib/llm/src/block_manager/pool.rs:447-466 ``match_sequence_hashes``).

Emits stored/removed KV events (the contract the KV-aware router indexes
on — reference: vLLM KVEvents ingested via lib/llm/src/kv_router/
publisher.rs; here the engine is native so events come straight from the
allocator).  ``stored`` fires when a block completes; ``removed`` fires when
a cached block is *evicted* (not when its sequence finishes — the content is
still resident and discoverable until then).

Block hashing matches the router's scheme: xxh3_64 over
(parent_hash, block token ids) with seed 1337 (reference:
lib/llm/src/kv_router/indexer.rs:64,122).
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable

from dynamo_tpu.llm.kv_router.hashing import HASH_SEED, compute_block_hashes  # noqa: F401


@dataclass
class KvEvent:
    kind: str                    # "stored" | "removed" | "cleared"
    block_hashes: list[int]
    parent_hash: int | None = None
    token_count: int = 0


@dataclass
class SequenceBlocks:
    block_ids: list[int] = field(default_factory=list)
    published_hashes: list[int] = field(default_factory=list)
    cached_tokens: int = 0       # prefix tokens reused from the registry


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size blocks with an
    LRU prefix-cache reuse tier.

    Block states: **free** (no content) → **in use** (refcount ≥ 1, owned by
    one or more sequences) → **cached** (refcount 0, content retained,
    evictable LRU) → free again on eviction.  Only *complete* blocks (hash
    registered via ``publish_stored``) enter the cached state.
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        *,
        event_sink: Callable[[KvEvent], None] | None = None,
        watermark: float = 0.01,
        enable_prefix_caching: bool = True,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.event_sink = event_sink
        self.enable_prefix_caching = enable_prefix_caching
        self.watermark_blocks = max(1, int(num_blocks * watermark))
        self._free: deque[int] = deque(range(num_blocks))
        self._cached: OrderedDict[int, None] = OrderedDict()  # block -> None, LRU
        self._ref: dict[int, int] = {}            # block -> refcount (in-use only)
        self._block_hash: dict[int, int] = {}     # block -> registered hash
        self._hash_to_block: dict[int, int] = {}  # hash -> resident block
        self._sequences: dict[str, SequenceBlocks] = {}
        # observability
        self.prefix_cached_tokens_total = 0
        self.prefix_hits_total = 0

    # -- capacity ----------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        """Allocatable capacity: truly-free plus evictable cached blocks."""
        return len(self._free) + len(self._cached)

    @property
    def cached_blocks(self) -> int:
        return len(self._cached)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def usage(self) -> float:
        return self.used_blocks / self.num_blocks

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def can_allocate(self, num_tokens: int) -> bool:
        return self.free_blocks - self.blocks_needed(num_tokens) >= self.watermark_blocks

    # -- block lifecycle helpers ------------------------------------------
    def _take_block(self, evicted_hashes: list[int]) -> int | None:
        """Pop a free block, evicting the LRU cached block if needed."""
        if self._free:
            return self._free.popleft()
        if self._cached:
            bid, _ = self._cached.popitem(last=False)
            h = self._block_hash.pop(bid, None)
            if h is not None and self._hash_to_block.get(h) == bid:
                del self._hash_to_block[h]
                evicted_hashes.append(h)
            return bid
        return None

    def _incref(self, bid: int) -> None:
        if bid in self._cached:  # cached → in use (content kept)
            del self._cached[bid]
        self._ref[bid] = self._ref.get(bid, 0) + 1

    def _decref(self, bid: int) -> None:
        ref = self._ref.get(bid, 0) - 1
        if ref > 0:
            self._ref[bid] = ref
            return
        self._ref.pop(bid, None)
        if bid in self._block_hash:
            # complete + registered: retain content for future prefix hits
            self._cached[bid] = None
        else:
            self._free.append(bid)

    def _emit_removed(self, hashes: list[int]) -> None:
        if hashes and self.event_sink:
            self.event_sink(KvEvent(kind="removed", block_hashes=hashes))

    # -- allocation --------------------------------------------------------
    def _match(self, token_ids: list[int] | None) -> list[tuple[int, int]]:
        """Leading (hash, block) pairs resident in the registry, capped so at
        least one prompt token is left to prefill (the model must still run
        to produce next-token logits)."""
        if not self.enable_prefix_caching or not token_ids:
            return []
        matched: list[tuple[int, int]] = []
        for h in compute_block_hashes(token_ids, self.block_size):
            bid = self._hash_to_block.get(h)
            if bid is None:
                break
            matched.append((h, bid))
        while matched and len(matched) * self.block_size >= len(token_ids):
            matched.pop()
        return matched

    def match_prefix(self, token_ids: list[int]) -> int:
        """Number of prompt tokens resident in the registry."""
        return len(self._match(token_ids)) * self.block_size

    def allocate_sequence(
        self, seq_id: str, num_tokens: int, token_ids: list[int] | None = None
    ) -> tuple[list[int], int] | None:
        """Allocate the block table for a new sequence of ``num_tokens``
        positions.  When ``token_ids`` (the known prompt) is given, leading
        complete blocks already resident are *shared* instead of allocated:
        returns (block_ids, cached_tokens) where the first
        ``cached_tokens // block_size`` entries are reused blocks the caller
        must not write.  None ⇒ OOM (nothing claimed)."""
        matched = self._match(token_ids)
        needed = self.blocks_needed(num_tokens) - len(matched)
        # claim matched blocks FIRST (removes them from the evictable set),
        # then check capacity against what is genuinely left — a matched
        # block sitting in the cached LRU must not be counted as allocatable
        for _, bid in matched:
            self._incref(bid)
        if needed > self.free_blocks:
            for _, bid in matched:  # roll back: nothing claimed on OOM
                self._decref(bid)
            return None
        evicted: list[int] = []
        fresh: list[int] = []
        for _ in range(max(needed, 0)):
            bid = self._take_block(evicted)
            assert bid is not None  # guaranteed by the capacity check
            self._ref[bid] = 1
            fresh.append(bid)
        self._emit_removed(evicted)
        cached_tokens = len(matched) * self.block_size
        self._sequences[seq_id] = SequenceBlocks(
            block_ids=[bid for _, bid in matched] + fresh,
            published_hashes=[h for h, _ in matched],
            cached_tokens=cached_tokens,
        )
        if cached_tokens:
            self.prefix_hits_total += 1
            self.prefix_cached_tokens_total += cached_tokens
        return self._sequences[seq_id].block_ids[:], cached_tokens

    def append_slot(self, seq_id: str, context_len: int) -> int | None:
        """Slot (flat cache index) for token at position ``context_len - 1``,
        growing the block table if the token starts a new block.  None ⇒ OOM."""
        return self.append_slots(seq_id, context_len, 1)

    def append_slots(self, seq_id: str, context_len: int, steps: int,
                     max_pos: int | None = None) -> int | None:
        """Ensure the block table covers positions ``context_len - 1`` through
        ``context_len - 2 + steps`` (multi-step decode pre-allocates the whole
        window so the device can derive per-step slots from the block table).
        Returns the first position's slot, or None on OOM (nothing grown
        partially)."""
        seq = self._sequences[seq_id]
        pos = context_len - 1
        last_pos = pos + steps - 1
        if max_pos is not None:
            last_pos = min(last_pos, max_pos)
        needed = last_pos // self.block_size + 1 - len(seq.block_ids)
        if needed > self.free_blocks:
            return None
        evicted: list[int] = []
        for _ in range(needed):
            bid = self._take_block(evicted)
            assert bid is not None
            self._ref[bid] = 1
            seq.block_ids.append(bid)
        self._emit_removed(evicted)
        return seq.block_ids[pos // self.block_size] * self.block_size + pos % self.block_size

    def adopt_sequence(self, seq_id: str, block_ids: list[int]) -> None:
        """Register blocks reserved earlier (disagg: reserved before remote
        prefill, adopted when the sequence starts decoding)."""
        self._sequences[seq_id] = SequenceBlocks(block_ids=list(block_ids))

    def reserve_blocks(self, num_tokens: int) -> list[int] | None:
        """Take blocks off the free list without a sequence (disagg decode
        side reserves the landing zone for remotely-prefilled KV)."""
        needed = self.blocks_needed(num_tokens)
        if needed > self.free_blocks:
            return None
        evicted: list[int] = []
        out = []
        for _ in range(needed):
            bid = self._take_block(evicted)
            assert bid is not None
            self._ref[bid] = 1
            out.append(bid)
        self._emit_removed(evicted)
        return out

    def release_blocks(self, block_ids: list[int]) -> None:
        for b in block_ids:
            self._decref(b)

    def block_ids(self, seq_id: str) -> list[int]:
        return list(self._sequences[seq_id].block_ids)

    def cached_tokens(self, seq_id: str) -> int:
        seq = self._sequences.get(seq_id)
        return seq.cached_tokens if seq else 0

    def free_sequence(self, seq_id: str) -> None:
        """Sequence finished: decref its blocks.  Registered (complete)
        blocks whose refcount hits zero stay resident in the LRU cache for
        future prefix hits; ``removed`` events fire only on eviction."""
        seq = self._sequences.pop(seq_id, None)
        if seq is None:
            return
        for b in seq.block_ids:
            self._decref(b)

    def clear_published(self) -> int:
        """Admin flush (reference: http clear_kv_blocks): drop the whole
        reuse registry — cached blocks are freed, in-use registered blocks
        unregister — and tell routers this worker's cache is gone.  Running
        sequences keep their blocks; their hashes simply re-publish as
        future blocks complete."""
        forgotten = set(self._hash_to_block)
        for seq in self._sequences.values():
            forgotten.update(seq.published_hashes)
            seq.published_hashes = []
        cleared = len(forgotten)
        self._hash_to_block.clear()
        self._block_hash.clear()
        while self._cached:
            bid, _ = self._cached.popitem(last=False)
            self._free.append(bid)
        if self.event_sink:
            self.event_sink(KvEvent(kind="cleared", block_hashes=[]))
        return cleared

    # -- events ------------------------------------------------------------
    def publish_stored(self, seq_id: str, token_ids: list[int]) -> None:
        """Emit stored events for newly-completed full blocks of ``seq_id``
        and register them for prefix reuse."""
        seq = self._sequences.get(seq_id)
        if seq is None:
            return
        hashes = compute_block_hashes(token_ids, self.block_size)
        new = hashes[len(seq.published_hashes):]
        if not new:
            return
        parent = seq.published_hashes[-1] if seq.published_hashes else None
        if self.enable_prefix_caching:
            for idx in range(len(seq.published_hashes), len(hashes)):
                if idx >= len(seq.block_ids):
                    break
                h, bid = hashes[idx], seq.block_ids[idx]
                # first writer wins: a hash already resident elsewhere keeps
                # its mapping; this block simply stays unregistered
                if h not in self._hash_to_block and bid not in self._block_hash:
                    self._hash_to_block[h] = bid
                    self._block_hash[bid] = h
        seq.published_hashes = hashes
        if self.event_sink:
            self.event_sink(
                KvEvent(
                    kind="stored",
                    block_hashes=new,
                    parent_hash=parent,
                    token_count=len(new) * self.block_size,
                )
            )
