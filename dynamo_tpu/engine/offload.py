"""Tiered KV offload for the serving engine: G2 host → G3 disk → G4 remote.

Built ON the KV block manager (``llm/block_manager``): the tiers are a
:class:`KvBlockManager` (host / disk / remote BlockPools over the uniform
Storage interface) and every block movement goes through
:meth:`OffloadManager.insert_sync` — the reference's engine cache IS its
block manager (lib/llm/src/block_manager.rs:90; offload chain
offload.rs:77-80; G4 remote tier block_manager.rs:68-81), and this adapter
is the serving-side mount of the same machinery.

- **offload**: when the allocator evicts a registered block from device HBM,
  the engine serializes that block's cache-pytree slice (works for any
  family layout, llama k/v or DeepSeek latent/rope) into one host block;
  host-LRU evictions cascade down-tier (disk, then a remote
  ``BlockStoreServer`` over DCN) read-before-overwrite, so content only
  disappears when it falls off the BOTTOM tier.
- **restore**: prompt matching extends past device-resident blocks into
  these tiers; hits are pinned at match time (whichever tier holds them)
  and scattered into freshly-allocated device blocks right before the tail
  prefill.  All calls are synchronous — this runs on the engine's device
  thread (RemoteStorage is blocking-socket by design).

Payload layout: per block, the concatenated raw bytes of each cache leaf
slice ``leaf[:, block_id]`` in sorted leaf-name order.
"""

from __future__ import annotations

import pathlib

import numpy as np

from dynamo_tpu.llm.block_manager.manager import KvbmConfig, KvBlockManager
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("engine.offload")


class HostOffloadTier:
    """Serving-side mount of the tiered block manager (G2/G3/G4)."""

    def __init__(
        self, num_blocks: int, leaf_shapes: dict, leaf_dtypes: dict,
        *, disk_blocks: int = 0, disk_path=None, remote_addr: str | None = None,
    ):
        self._names = sorted(leaf_shapes)
        self._shapes = {n: tuple(leaf_shapes[n]) for n in self._names}
        self._dtypes = {n: np.dtype(leaf_dtypes[n]) for n in self._names}
        self._sizes = {
            n: int(np.prod(self._shapes[n])) * self._dtypes[n].itemsize
            for n in self._names
        }
        self.block_nbytes = sum(self._sizes.values())
        self._disk_path = None
        if disk_blocks:
            import os
            import uuid

            # unique per tier: a fixed shared path would let a second
            # engine's mode="w+" memmap truncate this engine's live pool
            self._disk_path = pathlib.Path(
                disk_path
                or f"/tmp/dynamo_tpu_g3.{os.getpid()}.{uuid.uuid4().hex[:8]}.blocks"
            )
        self.kvbm = KvBlockManager(
            KvbmConfig(
                dtype=np.uint8,
                payload_shape=(self.block_nbytes,),
                device_blocks=0,  # G1 is the engine's own paged cache
                host_blocks=num_blocks,
                disk_blocks=disk_blocks,
                disk_path=None if self._disk_path is None else str(self._disk_path),
                remote_address=remote_addr,
            )
        )
        self.tiers = [self.kvbm.pools[t] for t in self.kvbm.tier_order]
        self.tier_names = [t.value for t in self.kvbm.tier_order]
        logger.info(
            "offload tiers %s (block payload %d bytes — size a G4 store "
            "with --nbytes %d)",
            "→".join(self.tier_names), self.block_nbytes, self.block_nbytes,
        )
        self.evict_observer = None  # engine hook: hash left EVERY tier
        self.offloads = 0
        self.restores = 0
        self._tier_restores = [0] * len(self.tiers)

    # convenience views (existing tests/benchmarks address the host pool)
    @property
    def pool(self):
        return self.tiers[0]

    @property
    def disk(self):
        return self.tiers[1] if "g3" in self.tier_names else None

    # -- offload (device eviction → host, cascading further down) -----------
    def put(self, seq_hash: int, leaves: dict) -> bool:
        """Store one evicted block's content; dedupes against the HOST tier
        only — a hash that previously cascaded to disk/remote gets a fresh
        host copy here, so a hot prefix that keeps cycling through device
        eviction is re-promoted to the fastest tier instead of being pinned
        to the bottom of the cascade forever (the stale lower-tier copy
        ages out of its LRU).  False when no tier can take it (full of
        pinned blocks).  A host block this put evicts cascades down-tier
        before being overwritten (OffloadManager.insert_sync)."""
        if self.tiers[0].has_hash(seq_hash):
            return True
        buf = np.concatenate(
            [
                np.ascontiguousarray(np.asarray(leaves[n])).view(np.uint8).ravel()
                for n in self._names
            ]
        )
        ok = self.kvbm.offload.insert_sync(
            self.kvbm.tier_order[0], buf[None], seq_hash,
            on_fully_evicted=self._on_fully_evicted,
        )
        if ok:
            self.offloads += 1
        return ok

    def _on_fully_evicted(self, seq_hash: int) -> None:
        if self.evict_observer is not None:
            self.evict_observer(seq_hash)

    # -- restore (any tier → device) -----------------------------------------
    def has(self, seq_hash: int) -> bool:
        return any(p.has_hash(seq_hash) for p in self.tiers)

    def pin(self, seq_hash: int) -> bool:
        """Claim a block for an upcoming restore so interleaved offloads
        can't evict it between match and prefill (whichever tier holds it)."""
        return any(p.match_hash(seq_hash) is not None for p in self.tiers)

    def unpin(self, seq_hash: int) -> None:
        for p in self.tiers:
            bid = p.peek_hash(seq_hash)
            if bid is not None:
                p.release(bid)
                return

    def read_pinned(self, seq_hash: int) -> dict | None:
        """Deserialize a pinned block's leaves and release the pin, from
        whichever tier holds it (host, disk memmap, or the remote store
        over DCN — RemoteStorage reads are blocking by design)."""
        out = self.read_pinned_many([seq_hash])
        return out.get(seq_hash)

    def read_pinned_many(self, seq_hashes: list[int]) -> dict[int, dict]:
        """Batched restore: ONE storage read per tier for all the hashes it
        holds (a 32-block G4 prefix costs one DCN round trip, not 32), pins
        released.  Missing hashes are absent from the result."""
        out: dict[int, dict] = {}
        remaining = list(seq_hashes)
        for i, p in enumerate(self.tiers):
            if not remaining:
                break
            held = [(h, p.peek_hash(h)) for h in remaining]
            held = [(h, bid) for h, bid in held if bid is not None]
            if not held:
                continue
            bufs = p.read([bid for _, bid in held])
            for (h, bid), buf in zip(held, bufs):
                p.release(bid)
                out[h] = self._deserialize(buf)
            self._tier_restores[i] += len(held)
            self.restores += len(held)
            got = {h for h, _ in held}
            remaining = [h for h in remaining if h not in got]
        return out

    def _deserialize(self, buf: np.ndarray) -> dict:
        out = {}
        offset = 0
        for n in self._names:
            size = self._sizes[n]
            out[n] = (
                buf[offset : offset + size].view(self._dtypes[n]).reshape(self._shapes[n])
            )
            offset += size
        return out

    def clear(self) -> None:
        """Admin flush: forget everything except blocks pinned for an
        in-flight restore (clear_kv_blocks keeps running sequences' state,
        mirroring the allocator's clear_published)."""
        for p in self.tiers:
            for h in p.registered_hashes():
                if p.ref_count(h) > 0:
                    continue
                p.drop_hash(h)

    def close(self) -> None:
        """Release every tier's backing (disk memmap deleted, remote
        connections closed)."""
        for p in self.tiers:
            try:
                p.storage.close()
            except Exception:  # noqa: BLE001
                pass
        if self._disk_path is not None:
            self._disk_path.unlink(missing_ok=True)

    def stats(self) -> dict:
        host = self.tiers[0]
        out = {
            "host_blocks_total": host.num_blocks,
            "host_blocks_used": host.num_blocks - host.free_count,
            "host_offloads_total": self.offloads,
            "host_restores_total": self.restores,
            "host_evictions": host.evictions,
        }
        inserts = self.kvbm.offload.tier_inserts
        for name, p, restores in zip(
            self.tier_names[1:], self.tiers[1:], self._tier_restores[1:]
        ):
            label = {"g3": "disk", "g4": "remote"}.get(name, name)
            out.update(
                {
                    f"{label}_blocks_total": p.num_blocks,
                    f"{label}_spills_total": inserts.get(name, 0),
                    f"{label}_restores_total": restores,
                    f"{label}_evictions": p.evictions,
                }
            )
        return out
