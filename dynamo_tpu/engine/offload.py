"""G2 host-DRAM offload tier for the serving engine.

Built on the KV block manager's pool machinery (``llm/block_manager``:
BlockPool lifecycle/LRU/registry + HostStorage) — the reference's engine
cache IS its block manager (lib/llm/src/block_manager.rs:90, G1→G2 offload
offload.rs:77-80); here the device tier is the engine's paged cache and this
tier catches blocks evicted from it:

- **offload**: when the allocator evicts a registered block from device HBM,
  the engine serializes that block's cache-pytree slice (works for any
  family layout, llama k/v or DeepSeek latent/rope) into one host block;
- **restore**: prompt matching extends past device-resident blocks into this
  tier; hits are pinned at match time and scattered into freshly-allocated
  device blocks right before the tail prefill.
"""

from __future__ import annotations

import pathlib

import numpy as np

from dynamo_tpu.llm.block_manager.pool import BlockPool
from dynamo_tpu.llm.block_manager.storage import HostStorage
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("engine.offload")


class HostOffloadTier:
    """Hash-addressed host pool of serialized KV blocks (G2), with an
    optional G3 spill: blocks evicted from the host LRU cascade to a
    disk-backed pool (np.memmap SSD tier) and restore from there on a
    later prefix hit — the reference's G1→G2→G3 offload chain
    (lib/llm/src/block_manager/offload.rs).

    Payload layout: per block, the concatenated raw bytes of each cache leaf
    slice ``leaf[:, block_id]`` in sorted leaf-name order.
    """

    def __init__(
        self, num_blocks: int, leaf_shapes: dict, leaf_dtypes: dict,
        *, disk_blocks: int = 0, disk_path=None,
    ):
        self._names = sorted(leaf_shapes)
        self._shapes = {n: tuple(leaf_shapes[n]) for n in self._names}
        self._dtypes = {n: np.dtype(leaf_dtypes[n]) for n in self._names}
        self._sizes = {
            n: int(np.prod(self._shapes[n])) * self._dtypes[n].itemsize
            for n in self._names
        }
        self.block_nbytes = sum(self._sizes.values())
        self.pool = BlockPool(
            HostStorage(num_blocks, (self.block_nbytes,), np.uint8), tier_name="g2"
        )
        self.disk: BlockPool | None = None
        self._disk_path = None
        if disk_blocks:
            import os
            import uuid

            from dynamo_tpu.llm.block_manager.storage import DiskStorage

            # unique per tier: a fixed shared path would let a second
            # engine's mode="w+" memmap truncate this engine's live pool
            self._disk_path = pathlib.Path(
                disk_path
                or f"/tmp/dynamo_tpu_g3.{os.getpid()}.{uuid.uuid4().hex[:8]}.blocks"
            )
            self.disk = BlockPool(
                DiskStorage(
                    disk_blocks, (self.block_nbytes,), np.uint8,
                    path=self._disk_path,
                ),
                tier_name="g3",
            )
            self.disk.evict_sink = self._on_disk_evict
        self._host_evicted_hash: int | None = None
        self.pool.evict_sink = self._on_host_evict
        self.evict_observer = None  # engine hook: hash left EVERY tier
        self.offloads = 0
        self.restores = 0
        self.disk_spills = 0
        self.disk_restores = 0

    # -- eviction cascade ----------------------------------------------------
    def _on_host_evict(self, seq_hash: int) -> None:
        # allocate() evicted this hash; the caller (put) spills its bytes
        # to disk before overwriting the host block
        self._host_evicted_hash = seq_hash

    def _on_disk_evict(self, seq_hash: int) -> None:
        if self.evict_observer is not None:
            self.evict_observer(seq_hash)

    def _spill_to_disk(self, seq_hash: int, host_bid: int) -> None:
        """Copy an evicted host block's (still-resident) bytes down-tier."""
        if self.disk is None or self.disk.has_hash(seq_hash):
            self._notify_if_gone(seq_hash)
            return
        dbid = self.disk.allocate()
        if dbid is None:
            self._notify_if_gone(seq_hash)
            return
        self.disk.write([dbid], self.pool.read([host_bid]))
        self.disk.complete(dbid, 0)
        self.disk.register(dbid, seq_hash)
        self.disk.release(dbid)
        self.disk_spills += 1

    def _notify_if_gone(self, seq_hash: int) -> None:
        if not self.has(seq_hash) and self.evict_observer is not None:
            self.evict_observer(seq_hash)

    # -- offload (device eviction → host) -----------------------------------
    def put(self, seq_hash: int, leaves: dict) -> bool:
        """Store one evicted block's content; dedupes by hash.  False when
        the tier is full of pinned blocks (offload skipped).  A host block
        this put evicts cascades to the disk tier first."""
        if self.pool.has_hash(seq_hash):
            return True
        self._host_evicted_hash = None
        bid = self.pool.allocate()  # evicts host LRU if needed
        if bid is None:
            return False
        if self._host_evicted_hash is not None:
            self._spill_to_disk(self._host_evicted_hash, bid)
            self._host_evicted_hash = None
        buf = np.concatenate(
            [
                np.ascontiguousarray(np.asarray(leaves[n])).view(np.uint8).ravel()
                for n in self._names
            ]
        )
        self.pool.write([bid], buf[None])
        self.pool.complete(bid, 0)
        self.pool.register(bid, seq_hash)
        self.pool.release(bid)  # park in the inactive LRU (evictable)
        self.offloads += 1
        return True

    # -- restore (host/disk → device) ----------------------------------------
    def has(self, seq_hash: int) -> bool:
        return self.pool.has_hash(seq_hash) or (
            self.disk is not None and self.disk.has_hash(seq_hash)
        )

    def pin(self, seq_hash: int) -> bool:
        """Claim a block for an upcoming restore so interleaved offloads
        can't evict it between match and prefill (whichever tier holds it)."""
        if self.pool.match_hash(seq_hash) is not None:
            return True
        return self.disk is not None and self.disk.match_hash(seq_hash) is not None

    def unpin(self, seq_hash: int) -> None:
        bid = self.pool.peek_hash(seq_hash)
        if bid is not None:
            self.pool.release(bid)
            return
        if self.disk is not None:
            dbid = self.disk.peek_hash(seq_hash)
            if dbid is not None:
                self.disk.release(dbid)

    def read_pinned(self, seq_hash: int) -> dict | None:
        """Deserialize a pinned block's leaves and release the pin; disk
        hits count as restores from G3."""
        bid = self.pool.peek_hash(seq_hash)
        if bid is None:
            if self.disk is None:
                return None
            dbid = self.disk.peek_hash(seq_hash)
            if dbid is None:
                return None
            buf = self.disk.read([dbid])[0]
            self.disk.release(dbid)
            self.disk_restores += 1
            self.restores += 1
            return self._deserialize(buf)
        buf = self.pool.read([bid])[0]
        self.pool.release(bid)
        self.restores += 1
        return self._deserialize(buf)

    def _deserialize(self, buf: np.ndarray) -> dict:
        out = {}
        offset = 0
        for n in self._names:
            size = self._sizes[n]
            out[n] = (
                buf[offset : offset + size].view(self._dtypes[n]).reshape(self._shapes[n])
            )
            offset += size
        return out

    def clear(self) -> None:
        """Admin flush: forget everything except blocks pinned for an
        in-flight restore (clear_kv_blocks keeps running sequences' state,
        mirroring the allocator's clear_published)."""
        for h in self.pool.registered_hashes():
            if self.pool.ref_count(h) > 0:
                continue
            self.pool.drop_hash(h)
        if self.disk is not None:
            for h in self.disk.registered_hashes():
                if self.disk.ref_count(h) > 0:
                    continue
                self.disk.drop_hash(h)

    def close(self) -> None:
        """Release the disk memmap and delete its backing file."""
        if self.disk is not None:
            try:
                self.disk.storage.close()
            except Exception:  # noqa: BLE001
                pass
            if self._disk_path is not None:
                self._disk_path.unlink(missing_ok=True)
            self.disk = None

    def stats(self) -> dict:
        out = {
            "host_blocks_total": self.pool.num_blocks,
            "host_blocks_used": self.pool.num_blocks - self.pool.free_count,
            "host_offloads_total": self.offloads,
            "host_restores_total": self.restores,
            "host_evictions": self.pool.evictions,
        }
        if self.disk is not None:
            out.update(
                disk_blocks_total=self.disk.num_blocks,
                disk_spills_total=self.disk_spills,
                disk_restores_total=self.disk_restores,
                disk_evictions=self.disk.evictions,
            )
        return out
