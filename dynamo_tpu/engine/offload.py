"""G2 host-DRAM offload tier for the serving engine.

Built on the KV block manager's pool machinery (``llm/block_manager``:
BlockPool lifecycle/LRU/registry + HostStorage) — the reference's engine
cache IS its block manager (lib/llm/src/block_manager.rs:90, G1→G2 offload
offload.rs:77-80); here the device tier is the engine's paged cache and this
tier catches blocks evicted from it:

- **offload**: when the allocator evicts a registered block from device HBM,
  the engine serializes that block's cache-pytree slice (works for any
  family layout, llama k/v or DeepSeek latent/rope) into one host block;
- **restore**: prompt matching extends past device-resident blocks into this
  tier; hits are pinned at match time and scattered into freshly-allocated
  device blocks right before the tail prefill.
"""

from __future__ import annotations

import numpy as np

from dynamo_tpu.llm.block_manager.pool import BlockPool
from dynamo_tpu.llm.block_manager.storage import HostStorage
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("engine.offload")


class HostOffloadTier:
    """Hash-addressed host pool of serialized KV blocks (G2).

    Payload layout: per block, the concatenated raw bytes of each cache leaf
    slice ``leaf[:, block_id]`` in sorted leaf-name order.
    """

    def __init__(self, num_blocks: int, leaf_shapes: dict, leaf_dtypes: dict):
        self._names = sorted(leaf_shapes)
        self._shapes = {n: tuple(leaf_shapes[n]) for n in self._names}
        self._dtypes = {n: np.dtype(leaf_dtypes[n]) for n in self._names}
        self._sizes = {
            n: int(np.prod(self._shapes[n])) * self._dtypes[n].itemsize
            for n in self._names
        }
        self.block_nbytes = sum(self._sizes.values())
        self.pool = BlockPool(
            HostStorage(num_blocks, (self.block_nbytes,), np.uint8), tier_name="g2"
        )
        self.offloads = 0
        self.restores = 0

    # -- offload (device eviction → host) -----------------------------------
    def put(self, seq_hash: int, leaves: dict) -> bool:
        """Store one evicted block's content; dedupes by hash.  False when
        the tier is full of pinned blocks (offload skipped)."""
        if self.pool.has_hash(seq_hash):
            return True
        bid = self.pool.allocate()  # evicts host LRU if needed
        if bid is None:
            return False
        buf = np.concatenate(
            [
                np.ascontiguousarray(np.asarray(leaves[n])).view(np.uint8).ravel()
                for n in self._names
            ]
        )
        self.pool.write([bid], buf[None])
        self.pool.complete(bid, 0)
        self.pool.register(bid, seq_hash)
        self.pool.release(bid)  # park in the inactive LRU (evictable)
        self.offloads += 1
        return True

    # -- restore (host → device) ---------------------------------------------
    def has(self, seq_hash: int) -> bool:
        return self.pool.has_hash(seq_hash)

    def pin(self, seq_hash: int) -> bool:
        """Claim a block for an upcoming restore so interleaved offloads
        can't evict it between match and prefill."""
        return self.pool.match_hash(seq_hash) is not None

    def unpin(self, seq_hash: int) -> None:
        bid = self.pool.peek_hash(seq_hash)
        if bid is not None:
            self.pool.release(bid)

    def read_pinned(self, seq_hash: int) -> dict | None:
        """Deserialize a pinned block's leaves and release the pin."""
        bid = self.pool.peek_hash(seq_hash)
        if bid is None:
            return None
        buf = self.pool.read([bid])[0]
        out = {}
        offset = 0
        for n in self._names:
            size = self._sizes[n]
            out[n] = (
                buf[offset : offset + size].view(self._dtypes[n]).reshape(self._shapes[n])
            )
            offset += size
        self.pool.release(bid)
        self.restores += 1
        return out

    def clear(self) -> None:
        """Admin flush: forget everything except blocks pinned for an
        in-flight restore (clear_kv_blocks keeps running sequences' state,
        mirroring the allocator's clear_published)."""
        for h in self.pool.registered_hashes():
            if self.pool.ref_count(h) > 0:
                continue
            self.pool.drop_hash(h)

    def stats(self) -> dict:
        return {
            "host_blocks_total": self.pool.num_blocks,
            "host_blocks_used": self.pool.num_blocks - self.pool.free_count,
            "host_offloads_total": self.offloads,
            "host_restores_total": self.restores,
            "host_evictions": self.pool.evictions,
        }
