"""Tiered KV offload for the serving engine: G2 host → G3 disk → G4 remote.

Built ON the KV block manager (``llm/block_manager``): the tiers are a
:class:`KvBlockManager` (host / disk / remote BlockPools over the uniform
Storage interface) and every block movement goes through
:meth:`OffloadManager.insert_sync` — the reference's engine cache IS its
block manager (lib/llm/src/block_manager.rs:90; offload chain
offload.rs:77-80; G4 remote tier block_manager.rs:68-81), and this adapter
is the serving-side mount of the same machinery.

- **offload**: when the allocator evicts a registered block from device HBM,
  the engine serializes that block's cache-pytree slice (works for any
  family layout, llama k/v or DeepSeek latent/rope) into one host block;
  host-LRU evictions cascade down-tier (disk, then a remote
  ``BlockStoreServer`` over DCN) read-before-overwrite, so content only
  disappears when it falls off the BOTTOM tier.
- **restore**: prompt matching extends past device-resident blocks into
  these tiers; hits are pinned at match time (whichever tier holds them)
  and scattered into freshly-allocated device blocks right before the tail
  prefill.  All calls are synchronous — this runs on the engine's device
  thread (RemoteStorage is blocking-socket by design).

Payload layout: per block, the concatenated raw bytes of each cache leaf
slice ``leaf[:, block_id]`` in sorted leaf-name order.
"""

from __future__ import annotations

import pathlib

import numpy as np

from dynamo_tpu.llm.block_manager.manager import KvbmConfig, KvBlockManager
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("engine.offload")


class HostOffloadTier:
    """Serving-side mount of the tiered block manager (G2/G3/G4)."""

    def __init__(
        self, num_blocks: int, leaf_shapes: dict, leaf_dtypes: dict,
        *, disk_blocks: int = 0, disk_path=None, remote_addr: str | None = None,
    ):
        self._names = sorted(leaf_shapes)
        self._shapes = {n: tuple(leaf_shapes[n]) for n in self._names}
        self._dtypes = {n: np.dtype(leaf_dtypes[n]) for n in self._names}
        self._sizes = {
            n: int(np.prod(self._shapes[n])) * self._dtypes[n].itemsize
            for n in self._names
        }
        self.block_nbytes = sum(self._sizes.values())
        self._disk_path = None
        if disk_blocks:
            import os
            import uuid

            # unique per tier: a fixed shared path would let a second
            # engine's mode="w+" memmap truncate this engine's live pool
            self._disk_path = pathlib.Path(
                disk_path
                or f"/tmp/dynamo_tpu_g3.{os.getpid()}.{uuid.uuid4().hex[:8]}.blocks"
            )
        self.kvbm = KvBlockManager(
            KvbmConfig(
                dtype=np.uint8,
                payload_shape=(self.block_nbytes,),
                device_blocks=0,  # G1 is the engine's own paged cache
                host_blocks=num_blocks,
                disk_blocks=disk_blocks,
                disk_path=None if self._disk_path is None else str(self._disk_path),
                remote_address=remote_addr,
            )
        )
        self.tiers = [self.kvbm.pools[t] for t in self.kvbm.tier_order]
        self.tier_names = [t.value for t in self.kvbm.tier_order]
        logger.info(
            "offload tiers %s (block payload %d bytes — size a G4 store "
            "with --nbytes %d)",
            "→".join(self.tier_names), self.block_nbytes, self.block_nbytes,
        )
        self.evict_observer = None  # engine hook: hash left EVERY tier
        self.offloads = 0
        self.restores = 0
        self._tier_restores = [0] * len(self.tiers)
        # hot-prefix pinning (prefetch subsystem): hashes restored at least
        # ``pin_hits`` times are pinned host-resident — a permanent ref in
        # the host pool keeps them out of the LRU, so a hot shared prefix
        # (system prompt) can never cascade to disk.  Budgeted to a
        # fraction of the host pool so pins cannot starve offloads (put()
        # fails when the tier is full of pins).
        self.pin_hits = knobs.get("DYN_PREFETCH_PIN_HITS")
        pin_max = knobs.get("DYN_PREFETCH_PIN_MAX")
        self.pin_max = pin_max if pin_max is not None else max(1, num_blocks // 4)
        # the engine clears this when the prefetch pager is off: nothing
        # would ever drain _hot_pending, and DYN_PREFETCH=0 must be
        # bookkeeping-free demand paging
        self.pin_enabled = True
        self._pins: dict[int, int] = {}       # hash -> pinned host block id
        self._hit_counts: dict[int, int] = {}  # hash -> restore count
        self._hot_pending: list[int] = []      # crossed the threshold, unpinned

    # convenience views (existing tests/benchmarks address the host pool)
    @property
    def pool(self):
        return self.tiers[0]

    @property
    def disk(self):
        return self.tiers[1] if "g3" in self.tier_names else None

    # -- offload (device eviction → host, cascading further down) -----------
    def put(self, seq_hash: int, leaves: dict) -> bool:
        """Store one evicted block's content; dedupes against the HOST tier
        only — a hash that previously cascaded to disk/remote gets a fresh
        host copy here, so a hot prefix that keeps cycling through device
        eviction is re-promoted to the fastest tier instead of being pinned
        to the bottom of the cascade forever (the stale lower-tier copy
        ages out of its LRU).  False when no tier can take it (full of
        pinned blocks).  A host block this put evicts cascades down-tier
        before being overwritten (OffloadManager.insert_sync)."""
        if self.tiers[0].has_hash(seq_hash):
            return True
        buf = np.concatenate(
            [
                np.ascontiguousarray(np.asarray(leaves[n])).view(np.uint8).ravel()
                for n in self._names
            ]
        )
        ok = self.kvbm.offload.insert_sync(
            self.kvbm.tier_order[0], buf[None], seq_hash,
            on_fully_evicted=self._on_fully_evicted,
        )
        if ok:
            self.offloads += 1
        return ok

    def _on_fully_evicted(self, seq_hash: int) -> None:
        if self.evict_observer is not None:
            self.evict_observer(seq_hash)

    # -- restore (any tier → device) -----------------------------------------
    def has(self, seq_hash: int) -> bool:
        return any(p.has_hash(seq_hash) for p in self.tiers)

    def locate(self, seq_hash: int) -> int | None:
        """Index of the highest (fastest) tier holding the hash, or None."""
        for i, p in enumerate(self.tiers):
            if p.has_hash(seq_hash):
                return i
        return None

    # -- predictive prefetch: up-tier promotion + hot-prefix pinning ---------
    def promote_to_host(self, seq_hashes: list[int]) -> int:
        """Bring lower-tier (disk/remote) blocks up into the host tier via
        the block manager's onboard path, so a restore that follows — the
        demand page-in at admission, or the pager's host→HBM pre-restore —
        is a DRAM read instead of disk/DCN IO.  Returns blocks moved.

        Runs on the engine's device thread (blocking IO by design, same as
        every other call here); ``asyncio.run`` hosts the async onboard's
        ``to_thread`` copies.  Host-LRU evictions the promotion causes
        cascade down-tier exactly like ``put`` (read-before-overwrite), so
        promotion never destroys content."""
        moved = 0
        host_key = self.kvbm.tier_order[0]
        for tier_idx in range(1, len(self.tiers)):
            pool = self.tiers[tier_idx]
            held = [
                h for h in seq_hashes
                if pool.has_hash(h) and not self.tiers[0].has_hash(h)
            ]
            if not held:
                continue
            import asyncio

            try:
                ids = asyncio.run(
                    self.kvbm.offload.onboard(
                        held, host_key, self.kvbm.tier_order[tier_idx],
                        on_fully_evicted=self._on_fully_evicted,
                    )
                )
            except Exception:  # noqa: BLE001 — promotion is best-effort
                logger.exception("tier promotion failed (%s)", self.tier_names[tier_idx])
                continue
            if ids is not None:
                moved += len(held)
        return moved

    def note_restored(self, seq_hash: int) -> None:
        """Restore-frequency bookkeeping: a hash that keeps paging back to
        the device is hot; past ``pin_hits`` restores it becomes a pin
        candidate (picked up by ``pin_hot``)."""
        if not self.pin_enabled:
            return
        n = self._hit_counts.get(seq_hash, 0) + 1
        self._hit_counts[seq_hash] = n
        if (
            n >= self.pin_hits
            and seq_hash not in self._pins
            and len(self._hot_pending) < self.pin_max  # bounded: pin budget
            and seq_hash not in self._hot_pending
        ):
            self._hot_pending.append(seq_hash)
        if len(self._hit_counts) > 4 * max(self.pin_max, 1):
            # bounded: forget the coldest half (insertion order approximates
            # age; hot hashes re-accumulate quickly)
            for h in list(self._hit_counts)[: len(self._hit_counts) // 2]:
                if h not in self._pins:
                    del self._hit_counts[h]

    def pin_hot(self) -> int:
        """Pin pending hot prefixes host-resident (a permanent pool ref
        keeps them out of the host LRU, so they can never cascade to
        disk).  Called from the engine's prefetch loop — never on the
        demand path.  Returns newly pinned blocks."""
        if not self._hot_pending:
            return 0
        budget = self.pin_max - len(self._pins)
        # hot but currently below the host tier: one batched promotion for
        # the whole pending set (promote_to_host pays an event loop per
        # tier — per hash would put that inside the engine hot loop)
        below = [
            h for h in self._hot_pending[:budget]
            if h not in self._pins and not self.tiers[0].has_hash(h)
        ]
        if below:
            self.promote_to_host(below)
        pinned = 0
        while self._hot_pending and len(self._pins) < self.pin_max:
            h = self._hot_pending.pop(0)
            if h in self._pins:
                continue
            bid = self.tiers[0].match_hash(h)  # permanent ref = the pin
            if bid is None:
                continue
            self._pins[h] = bid
            pinned += 1
        if len(self._pins) >= self.pin_max:
            self._hot_pending.clear()
        return pinned

    def unpin_all(self) -> None:
        for h, bid in list(self._pins.items()):
            self.tiers[0].release(bid)
        self._pins.clear()
        self._hit_counts.clear()
        self._hot_pending.clear()

    def pin(self, seq_hash: int) -> bool:
        """Claim a block for an upcoming restore so interleaved offloads
        can't evict it between match and prefill (whichever tier holds it)."""
        return any(p.match_hash(seq_hash) is not None for p in self.tiers)

    def unpin(self, seq_hash: int) -> None:
        for p in self.tiers:
            bid = p.peek_hash(seq_hash)
            if bid is not None:
                p.release(bid)
                return

    def read_pinned(self, seq_hash: int) -> dict | None:
        """Deserialize a pinned block's leaves and release the pin, from
        whichever tier holds it (host, disk memmap, or the remote store
        over DCN — RemoteStorage reads are blocking by design)."""
        out = self.read_pinned_many([seq_hash])
        return out.get(seq_hash)

    def read_pinned_many(self, seq_hashes: list[int]) -> dict[int, dict]:
        """Batched restore: ONE storage read per tier for all the hashes it
        holds (a 32-block G4 prefix costs one DCN round trip, not 32), pins
        released.  Missing hashes are absent from the result."""
        out: dict[int, dict] = {}
        remaining = list(seq_hashes)
        for i, p in enumerate(self.tiers):
            if not remaining:
                break
            held = [(h, p.peek_hash(h)) for h in remaining]
            held = [(h, bid) for h, bid in held if bid is not None]
            if not held:
                continue
            bufs = p.read([bid for _, bid in held])
            for (h, bid), buf in zip(held, bufs):
                p.release(bid)
                out[h] = self._deserialize(buf)
                self.note_restored(h)
            self._tier_restores[i] += len(held)
            self.restores += len(held)
            got = {h for h, _ in held}
            remaining = [h for h in remaining if h not in got]
        return out

    def _deserialize(self, buf: np.ndarray) -> dict:
        out = {}
        offset = 0
        for n in self._names:
            size = self._sizes[n]
            out[n] = (
                buf[offset : offset + size].view(self._dtypes[n]).reshape(self._shapes[n])
            )
            offset += size
        return out

    def clear(self) -> None:
        """Admin flush: forget everything except blocks pinned for an
        in-flight restore (clear_kv_blocks keeps running sequences' state,
        mirroring the allocator's clear_published).  Hot-prefix pins are
        dropped first — they are cache, and an admin flush means forget."""
        self.unpin_all()
        for p in self.tiers:
            for h in p.registered_hashes():
                if p.ref_count(h) > 0:
                    continue
                p.drop_hash(h)

    def close(self) -> None:
        """Release every tier's backing (disk memmap deleted, remote
        connections closed)."""
        for p in self.tiers:
            try:
                p.storage.close()
            except Exception:  # noqa: BLE001
                pass
        if self._disk_path is not None:
            self._disk_path.unlink(missing_ok=True)

    def stats(self) -> dict:
        host = self.tiers[0]
        out = {
            "host_blocks_total": host.num_blocks,
            "host_blocks_used": host.num_blocks - host.free_count,
            "host_blocks_pinned": len(self._pins),
            "host_offloads_total": self.offloads,
            "host_restores_total": self.restores,
            "host_evictions": host.evictions,
        }
        inserts = self.kvbm.offload.tier_inserts
        for name, p, restores in zip(
            self.tier_names[1:], self.tiers[1:], self._tier_restores[1:]
        ):
            label = {"g3": "disk", "g4": "remote"}.get(name, name)
            out.update(
                {
                    f"{label}_blocks_total": p.num_blocks,
                    f"{label}_blocks_used": p.num_blocks - p.free_count,
                    f"{label}_spills_total": inserts.get(name, 0),
                    f"{label}_restores_total": restores,
                    f"{label}_evictions": p.evictions,
                }
            )
        return out

    def tiers_snapshot(self) -> dict:
        """Structured per-tier occupancy for the observability plane
        (ForwardPassMetrics.offload_tiers → dyn_worker_offload_blocks*)."""
        out = {}
        for i, (name, p) in enumerate(zip(self.tier_names, self.tiers)):
            row = {"blocks": p.num_blocks, "used": p.num_blocks - p.free_count}
            if i == 0:
                row["pinned"] = len(self._pins)
            out[name] = row
        return out
