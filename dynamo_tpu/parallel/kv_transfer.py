"""Cross-worker KV block transfer.

The TPU-native replacement for the reference's NIXL/RDMA plane (SURVEY.md
§2.5; strategy selection by src/dst locality mirrors
lib/llm/src/block_manager/block/transfer/strategy.rs:345): prefill and
decode engines live on separate mesh partitions/processes, so prefilled KV
blocks are shipped prefill→decode.

Paths, selected automatically per destination:
- **local/ICI (same process)**: the destination server is found in the
  process-local registry; blocks stay as device arrays end-to-end — the
  receiving engine's scatter moves them device-to-device (HBM copy on one
  chip, ICI when the engines sit on different chips of the slice).  No
  serialization, no host staging.
- **DCN/TCP**: device→host staging (``jax.device_get``), raw bf16 bytes over
  a TCP stream with the two-part codec, host→device scatter on the receiver.
  Works across hosts and processes.

Wire: header {seq_id, first_token, block_ids, parts} + payload bytes.
Streamed transfers (FlowKV-style, arxiv 2504.03775) ship one frame per
completed prefill chunk: the header additionally carries
{part_index, last, block_start} and the final frame alone holds the
sampled first token.  Legacy single-shot payloads are the degenerate
one-part stream (part_index=0, last=True) and decode unchanged.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Awaitable, Callable

import numpy as np

from dynamo_tpu.robustness.faults import FAULTS, KV_TRANSFER
from dynamo_tpu.runtime.codec import TwoPartMessage, encode_frame, read_two_part
from dynamo_tpu.utils import knobs
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("parallel.kv_transfer")


def resolve_dtype(name: str) -> np.dtype:
    """np.dtype, accepting accelerator dtypes (bfloat16 via ml_dtypes)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


# process-local transfer servers by address: same-process sends short-cut
# TCP entirely and hand device arrays straight to the sink
LOCAL_SERVERS: dict[str, "KvTransferServer"] = {}

# topology-prober payloads carry this seq-id prefix: servers ack them (so the
# sender times a real staging+frame+ack exchange) but never deliver them to
# the engine sink — probing must be invisible to decode state
PROBE_SEQ_PREFIX = "__dyn_topo_probe__/"


@dataclass
class KvTransferPayload:
    seq_id: str
    first_token: int
    block_ids: list[int]          # destination (decode-side) block ids
    # cache pytree restricted to the sequence's blocks, one named host array
    # per cache leaf — llama: {"k": [L, n, bs, kvh, d], "v": ...}; DeepSeek
    # MLA: latent + rope-key leaves with different trailing shapes
    blocks: dict[str, np.ndarray]
    # logprob of first_token under the prefill worker's distribution
    first_token_logprob: float | None = None
    # [[token_id, logprob], ...] alternatives for first_token (when asked)
    first_token_top_logprobs: list | None = None
    # streamed multi-part protocol: ``part_index`` orders the parts of one
    # sequence's transfer, ``last`` marks the stream-closing part (the only
    # one whose first_token* fields are meaningful — intermediates carry
    # first_token=-1), ``block_start`` is the part's offset into the
    # sequence's landing zone.  The defaults make every pre-existing
    # single-shot payload a well-formed one-part stream.
    part_index: int = 0
    last: bool = True
    block_start: int = 0
    # layer-wise granularity: a part may carry only layers
    # [layer_start, layer_start + layer_count) of its blocks' leading (layer)
    # axis, so the first layers of a block can leave before the block
    # finishes all layers.  layer_count == -1 means "all layers" — every
    # legacy frame is the all-layers degenerate case.
    layer_start: int = 0
    layer_count: int = -1


def split_layerwise(
    payload: KvTransferPayload, layers_per_part: int
) -> list[KvTransferPayload]:
    """Slice one payload into layer-range parts along the blocks' leading
    (layer) axis.  The final part inherits ``last`` and the first_token*
    fields; intermediates are ordinary non-final stream parts.  A payload
    whose arrays have fewer layers than ``layers_per_part`` round-trips as
    a single part."""
    if layers_per_part <= 0 or not payload.blocks:
        return [payload]
    n_layers = min(a.shape[0] for a in payload.blocks.values())
    if n_layers <= layers_per_part:
        return [payload]
    parts: list[KvTransferPayload] = []
    for start in range(0, n_layers, layers_per_part):
        count = min(layers_per_part, n_layers - start)
        final = start + count >= n_layers
        parts.append(KvTransferPayload(
            seq_id=payload.seq_id,
            first_token=payload.first_token if final else -1,
            block_ids=list(payload.block_ids),
            blocks={n: a[start:start + count] for n, a in payload.blocks.items()},
            first_token_logprob=payload.first_token_logprob if final else None,
            first_token_top_logprobs=(
                payload.first_token_top_logprobs if final else None
            ),
            part_index=payload.part_index + len(parts),
            last=payload.last and final,
            block_start=payload.block_start,
            layer_start=start,
            layer_count=count,
        ))
    return parts


def assemble_layers(parts: list[KvTransferPayload]) -> KvTransferPayload:
    """Stitch layer-range parts of one block range back into a full-depth
    payload (receiver-side twin of :func:`split_layerwise`; tolerates
    duplicates and arbitrary arrival order)."""
    if len(parts) == 1 and parts[0].layer_count < 0:
        return parts[0]
    by_start = {p.layer_start: p for p in parts}
    ordered = [by_start[k] for k in sorted(by_start)]
    final = max(parts, key=lambda p: p.layer_start)
    blocks = {
        name: np.concatenate([p.blocks[name] for p in ordered], axis=0)
        for name in ordered[0].blocks
    }
    return KvTransferPayload(
        seq_id=final.seq_id,
        first_token=final.first_token,
        block_ids=list(final.block_ids),
        blocks=blocks,
        first_token_logprob=final.first_token_logprob,
        first_token_top_logprobs=final.first_token_top_logprobs,
        part_index=final.part_index,
        last=final.last,
        block_start=final.block_start,
    )


class KvTransferServer:
    """Decode-worker side: receives KV payloads and hands them to a sink
    (typically ``engine.inject_blocks`` + completion notification)."""

    def __init__(
        self,
        sink: Callable[[KvTransferPayload], Awaitable[None]],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.sink = sink
        self.host = host
        self.port = port
        self._server: asyncio.Server | None = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        LOCAL_SERVERS[self.address] = self

    async def stop(self) -> None:
        LOCAL_SERVERS.pop(self.address, None)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def deliver_local(self, payload: KvTransferPayload) -> None:
        """Same-process fast path: blocks arrive as device arrays and skip
        the codec entirely (the ICI-class transfer)."""
        if payload.seq_id.startswith(PROBE_SEQ_PREFIX):
            return
        await self.sink(payload)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                frame = await read_two_part(reader)
                if frame is None:
                    return
                h = frame.header
                blocks: dict[str, np.ndarray] = {}
                offset = 0
                for part in h["parts"]:
                    dtype = resolve_dtype(part["dtype"])
                    shape = tuple(part["shape"])
                    size = int(np.prod(shape)) * dtype.itemsize
                    blocks[part["name"]] = np.frombuffer(
                        frame.payload[offset : offset + size], dtype
                    ).reshape(shape)
                    offset += size
                payload = KvTransferPayload(
                    seq_id=h["seq_id"],
                    first_token=h["first_token"],
                    first_token_logprob=h.get("first_token_logprob"),
                    first_token_top_logprobs=h.get("first_token_top_logprobs"),
                    block_ids=list(h["block_ids"]),
                    blocks=blocks,
                    # mixed-version compat: a pre-streaming sender omits the
                    # part fields — decode as a one-part stream; a
                    # pre-layerwise sender omits the layer fields — decode
                    # as an all-layers part
                    part_index=int(h.get("part_index", 0)),
                    last=bool(h.get("last", True)),
                    block_start=int(h.get("block_start", 0)),
                    layer_start=int(h.get("layer_start", 0)),
                    layer_count=int(h.get("layer_count", -1)),
                )
                if not payload.seq_id.startswith(PROBE_SEQ_PREFIX):
                    await self.sink(payload)
                writer.write(encode_frame(TwoPartMessage(header={"ok": True, "seq_id": h["seq_id"]})))
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()


# socket-class failures a pooled connection can hit mid-exchange: the
# cached connection is garbage (peer restarted, idle reset by a middlebox)
# but the payload is intact — evict and re-dial instead of failing the send
_RETRYABLE = (ConnectionError, asyncio.IncompleteReadError, BrokenPipeError, OSError)


class KvTransferClient:
    """Prefill-worker side: pooled connections to decode workers.

    Beyond pooling, the client measures each TCP exchange and keeps a
    per-destination bandwidth EWMA — the measured half of the router's
    transfer-cost model (hop class supplies the prior until a destination
    has been observed)."""

    def __init__(self, *, ewma_alpha: float = 0.25) -> None:
        self._conns: dict[str, tuple[asyncio.StreamReader, asyncio.StreamWriter, asyncio.Lock]] = {}
        self._ewma_alpha = ewma_alpha
        # address -> measured bytes/second EWMA over write→ack exchanges
        self.bandwidth_bps: dict[str, float] = {}
        self.evictions_total = 0

    async def _conn(self, address: str):
        entry = self._conns.get(address)
        if entry is not None and not entry[1].is_closing():
            return entry
        host, _, port = address.rpartition(":")
        # bound the dial: a black-holed peer (SYN into a dead route) would
        # otherwise park the send — and the prefill pump behind it — on the
        # kernel's connect timeout, which can be minutes
        dial_timeout = knobs.get("DYN_KV_DIAL_TIMEOUT_S")
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), timeout=dial_timeout
            )
        except asyncio.TimeoutError:
            raise ConnectionError(
                f"KV transfer dial to {address} timed out after {dial_timeout:.1f}s"
            ) from None
        entry = (reader, writer, asyncio.Lock())
        self._conns[address] = entry
        return entry

    def _evict(self, address: str, writer: asyncio.StreamWriter) -> None:
        """Drop a broken pooled connection — only if the pool still holds
        THIS writer (a concurrent sender may have re-dialed already)."""
        writer.close()
        entry = self._conns.get(address)
        if entry is not None and entry[1] is writer:
            del self._conns[address]
            self.evictions_total += 1

    def _observe(self, address: str, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds <= 0:
            return
        bps = nbytes / seconds
        prev = self.bandwidth_bps.get(address)
        self.bandwidth_bps[address] = (
            bps if prev is None else prev + self._ewma_alpha * (bps - prev)
        )

    async def send(self, address: str, payload: KvTransferPayload) -> None:
        # chaos seam: a failed KV shipment (the decode side's prefill wait
        # times out and degrades to a local prefill)
        FAULTS.check(KV_TRANSFER, seq_id=payload.seq_id)
        local = LOCAL_SERVERS.get(address)
        if local is not None:
            await local.deliver_local(payload)
            return

        # Host staging (layout copies + byte serialization of multi-MB KV
        # slices) runs OUTSIDE the per-connection lock and OFF the event
        # loop: concurrent shipments to one decode worker overlap their
        # staging with each other and with the socket round-trip below,
        # instead of serializing the whole copy→write→ack chain.  (numpy
        # releases the GIL for the bulk copies, so the executor thread
        # genuinely runs alongside the loop.)
        def stage() -> tuple[dict, bytes]:
            names = sorted(payload.blocks)
            arrays = [np.ascontiguousarray(payload.blocks[n]) for n in names]
            # bf16 numpy: ml_dtypes dtype name round-trips through np.dtype
            header = {
                "seq_id": payload.seq_id,
                "first_token": payload.first_token,
                "first_token_logprob": payload.first_token_logprob,
                "first_token_top_logprobs": payload.first_token_top_logprobs,
                "block_ids": payload.block_ids,
                "part_index": payload.part_index,
                "last": payload.last,
                "block_start": payload.block_start,
                "layer_start": payload.layer_start,
                "layer_count": payload.layer_count,
                "parts": [
                    {"name": n, "dtype": a.dtype.name, "shape": list(a.shape)}
                    for n, a in zip(names, arrays)
                ],
            }
            return header, b"".join(a.tobytes() for a in arrays)

        loop = asyncio.get_running_loop()
        header, body = await loop.run_in_executor(None, stage)
        frame = encode_frame(TwoPartMessage(header=header, payload=body))
        last_err: Exception | None = None
        for _attempt in range(2):
            reader, writer, lock = await self._conn(address)
            try:
                # only the write→ack round-trip holds the lock (frame
                # interleaving on one socket is the one thing that must
                # serialize)
                async with lock:
                    t0 = time.perf_counter()
                    writer.write(frame)
                    await writer.drain()
                    ack = await read_two_part(reader)
                    elapsed = time.perf_counter() - t0
            except _RETRYABLE as exc:
                # pooled connection died under us (peer restart / reset):
                # the payload never landed — evict and re-dial once
                self._evict(address, writer)
                last_err = exc
                continue
            if ack is None:
                # clean EOF before the ack: same remedy as a reset
                self._evict(address, writer)
                last_err = ConnectionError(
                    f"kv transfer to {address}: connection closed before ack"
                )
                continue
            if not ack.header.get("ok"):
                # the server SAW the frame and refused it — re-sending the
                # same bytes cannot help; fail loudly
                raise ConnectionError(f"kv transfer to {address} failed")
            self._observe(address, len(body), elapsed)
            return
        raise ConnectionError(
            f"kv transfer to {address} failed after re-dial: {last_err}"
        )

    async def close(self) -> None:
        for _, writer, _ in self._conns.values():
            writer.close()
        self._conns.clear()
