"""Pipeline parallelism over the ``pp`` mesh axis.

The model families stack per-layer weights on a leading layer axis
(``[L, ...]`` leaves), so pipeline stages fall out of sharding that axis:
stage ``s`` holds layers ``[s*L/S, (s+1)*L/S)`` and its slice of the
layer-stacked KV cache.  Execution is GPipe-style inference (no backward):
the batch splits into microbatches that stream through the stages, and
activations hop stage→stage with ``jax.lax.ppermute`` (ICI neighbor
exchange).  Total ticks = S + M - 1; the (S-1)-tick bubble amortizes as
M grows.

The reference's multi-node engine splits layers across nodes through the
serving engine (SURVEY.md §2.5 marks PP reserved); here PP is a mesh axis
like every other, composed by GSPMD outside the shard_map.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P


def pipeline_layer_stack(
    body: Callable,
    x: jnp.ndarray,             # [B, ...] activations entering layer 0
    aux,                        # pytree of [B, ...] per-row side inputs
    layer_params,               # pytree, leading axis L, sharded P("pp", ...)
    layer_cache,                # pytree, leading axis L, sharded P("pp", ...)
    mesh: Mesh,
    *,
    axis: str = "pp",
    microbatches: int | None = None,
):
    """Run ``x`` through all L stacked layers, pipelined over ``axis``.

    ``body(x_mb, aux_mb, w, cache_layer) -> (x_mb, cache_layer)`` applies ONE
    layer (single-layer slices of params/cache) to one microbatch.

    Returns ``(x_out [B, ...], layer_cache')`` with the cache's layer axis
    reassembled across stages.
    """
    stages = mesh.shape[axis]
    batch = x.shape[0]
    m_count = microbatches or stages
    if batch % m_count:
        raise ValueError(f"batch {batch} not divisible by {m_count} microbatches")
    mb = batch // m_count

    def stage_fn(x_full, aux_full, w_local, cache_local):
        stage = jax.lax.axis_index(axis)
        last = stages - 1
        perm = [(i, (i + 1) % stages) for i in range(stages)]

        x_chunks = x_full.reshape(m_count, mb, *x_full.shape[1:])
        aux_chunks = jax.tree.map(
            lambda a: a.reshape(m_count, mb, *a.shape[1:]), aux_full
        )

        def run_local_layers(x_in, aux_in, cache_loc):
            def one_layer(carry, layer_in):
                xc = carry
                w, c = layer_in
                xc, c = body(xc, aux_in, w, c)
                return xc, c

            return jax.lax.scan(one_layer, x_in, (w_local, cache_loc))

        cur0 = jnp.zeros((mb, *x_full.shape[1:]), x_full.dtype)
        ys0 = jnp.zeros((m_count, mb, *x_full.shape[1:]), x_full.dtype)

        def tick(t, state):
            cur, ys, cache_loc = state
            m = t - stage                      # this stage's microbatch index
            active = jnp.logical_and(m >= 0, m < m_count)
            mc = jnp.clip(m, 0, m_count - 1)
            x_in = jnp.where(stage == 0, x_chunks[jnp.clip(t, 0, m_count - 1)], cur)
            aux_in = jax.tree.map(lambda a: a[mc], aux_chunks)
            y, cache_new = run_local_layers(x_in, aux_in, cache_loc)
            # only active ticks commit cache writes (bubble ticks chew on
            # stale/garbage activations by design)
            cache_loc = jax.tree.map(
                lambda n, o: jnp.where(active, n, o), cache_new, cache_loc
            )
            ys = jnp.where(
                jnp.logical_and(active, stage == last), ys.at[mc].set(y), ys
            )
            cur = jax.lax.ppermute(y, axis, perm)
            return cur, ys, cache_loc

        cur, ys, cache_local = jax.lax.fori_loop(
            0, stages + m_count - 1, tick, (cur0, ys0, cache_local)
        )
        # the last stage holds the outputs; replicate them to every stage
        ys = jax.lax.psum(
            jnp.where(stage == last, ys, jnp.zeros_like(ys)), axis
        )
        return ys.reshape(batch, *x_full.shape[1:]), cache_local

    layer_spec = P(axis)
    # PARTIAL-manual shard_map: only the pp axis is manual (explicit
    # ppermute/psum between stages); every other mesh axis — tp in a
    # pp×tp mesh — stays automatic, so tp-sharded stage weights keep their
    # sharding inside the stage body and GSPMD inserts the tensor-parallel
    # collectives there.  This is what composes pipeline stages WITH
    # tensor-parallel weights instead of forcing pp to be the sole axis.
    fn = jax.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(
            P(),
            jax.tree.map(lambda _: P(), aux),
            jax.tree.map(lambda _: layer_spec, layer_params),
            jax.tree.map(lambda _: layer_spec, layer_cache),
        ),
        out_specs=(P(), jax.tree.map(lambda _: layer_spec, layer_cache)),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    # always trace through jit: the eager impl path of a PARTIAL-manual
    # shard_map trips an internal spec-unmatch check in jax 0.9 when
    # microbatches != stages; under jit (how serving always runs — this is
    # inlined into the engine's decode program, no extra compile) the same
    # program is valid.  NOTE for eager callers (tests, diagnostics): this
    # wrapper is fresh per call, so each eager invocation re-traces — wrap
    # your own jit around the model-level fn if you loop.
    return jax.jit(fn)(x, aux, layer_params, layer_cache)
