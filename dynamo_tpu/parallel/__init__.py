from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh, shard_pytree

__all__ = ["MeshConfig", "make_mesh", "shard_pytree"]
