"""Multi-host bootstrap.

Coordinates a multi-host JAX process group through the control plane
(reference: MultiNodeConfig lib/llm/src/engines.rs:44-60 + etcd
leader/worker barrier for engine bring-up; the engine-internal bootstrap —
torch.distributed/NCCL there — is ``jax.distributed.initialize`` + XLA
collectives over ICI/DCN here).

Flow: the leader (node_rank 0) publishes its coordinator address through a
LeaderBarrier; workers pick it up, everyone calls
``jax.distributed.initialize``, and the global mesh spans all hosts' devices.
"""

from __future__ import annotations

from dataclasses import dataclass

from dynamo_tpu.runtime.barrier import LeaderBarrier, WorkerBarrier
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("parallel.multihost")


@dataclass
class MultiNodeConfig:
    num_nodes: int = 1
    node_rank: int = 0
    leader_addr: str | None = None   # host:port of the jax coordinator

    @property
    def is_leader(self) -> bool:
        return self.node_rank == 0


async def bootstrap_multihost(
    kv,
    config: MultiNodeConfig,
    *,
    barrier_id: str = "jax-bootstrap",
    coordinator_port: int = 8476,
    timeout: float = 300.0,
) -> None:
    """Rendezvous + ``jax.distributed.initialize``.  No-op for single node."""
    if config.num_nodes <= 1:
        return
    import socket

    import jax

    import asyncio
    import functools

    loop = asyncio.get_running_loop()

    def initialize(addr: str, process_id: int) -> None:
        # jax.distributed.initialize blocks until every process connects to
        # the coordinator.  It must NOT run on the event loop: the leader's
        # barrier publish (and the runtime's lease keepalives) need the loop
        # while initialize waits for the other ranks.
        fn = functools.partial(
            jax.distributed.initialize,
            coordinator_address=addr,
            num_processes=config.num_nodes,
            process_id=process_id,
        )
        return fn()

    if config.is_leader:
        addr = config.leader_addr or f"{socket.gethostbyname(socket.gethostname())}:{coordinator_port}"
        leader = LeaderBarrier(kv, barrier_id, num_workers=config.num_nodes - 1)
        # publish before initialize so workers can join while the leader blocks
        sync_task = asyncio.ensure_future(leader.sync({"coordinator": addr}, timeout=timeout))
        try:
            await loop.run_in_executor(None, initialize, addr, 0)
        except BaseException:
            sync_task.cancel()  # don't leave the barrier task dangling
            raise
        await sync_task
    else:
        worker = WorkerBarrier(kv, barrier_id, worker_id=str(config.node_rank))
        data = await worker.sync(timeout=timeout)
        await loop.run_in_executor(None, initialize, data["coordinator"], config.node_rank)
    logger.info(
        "multihost up: rank %d/%d, %d global devices",
        config.node_rank, config.num_nodes, jax.device_count(),
    )
