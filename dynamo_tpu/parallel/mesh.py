"""Device mesh construction and pytree sharding.

The engine's parallelism is expressed entirely as a ``jax.sharding.Mesh``
with named axes + PartitionSpecs; XLA emits the collectives over ICI/DCN
(replaces the reference's delegation to NCCL inside engines —
SURVEY.md §2.5).

Axes (any may be 1): ``dp`` data, ``pp`` pipeline stage, ``tp`` tensor,
``ep`` expert, ``sp`` sequence/context.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

AXIS_ORDER = ("dp", "pp", "ep", "tp", "sp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1
    # first device index this mesh claims — lets two engines in one process
    # own DISJOINT partitions of the device set (disaggregated prefill and
    # decode engines each on their own sub-mesh)
    device_offset: int = 0

    def total(self) -> int:
        return self.dp * self.pp * self.ep * self.tp * self.sp

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    @classmethod
    def tp_only(cls, tp: int) -> "MeshConfig":
        return cls(tp=tp)


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a named mesh.  Defaults: all local devices on the ``tp`` axis.

    Axis order puts ``tp``/``sp`` innermost so tensor-parallel collectives
    ride the fastest ICI links (outer axes land on DCN for multi-host).
    """
    devices = devices if devices is not None else jax.devices()
    if config is None:
        config = MeshConfig(tp=len(devices))
    n = config.total()
    off = config.device_offset
    if off < 0 or off + n > len(devices):
        raise ValueError(
            f"mesh needs devices [{off}, {off + n}), have {len(devices)}"
        )
    device_array = np.asarray(devices[off : off + n]).reshape(
        [config.axis_sizes()[a] for a in AXIS_ORDER]
    )
    return Mesh(device_array, AXIS_ORDER)


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def host_bounce(x, target_platform: str):
    """Return ``x``, bounced through a host ndarray when it is a jax.Array
    on a different backend than ``target_platform``.

    device_put of a cross-backend jax.Array can leave a buffer that
    re-stages on every program execution taking it as an argument
    (measured ~150ms/arg/call on tunneled PJRT runtimes); a host ndarray
    transfers into a native, committed device buffer.  The single shared
    predicate for every transfer path (engine init, mesh placement).
    """
    if (
        isinstance(x, jax.Array)
        and next(iter(x.devices())).platform != target_platform
    ):
        return np.asarray(x)
    return x


def shard_pytree(tree, specs, mesh: Mesh):
    """Place a pytree on the mesh according to a matching specs pytree
    (cross-backend leaves host-bounce first — see ``host_bounce``)."""
    mesh_platform = mesh.devices.flat[0].platform

    def put(x, spec):
        return jax.device_put(
            host_bounce(x, mesh_platform), NamedSharding(mesh, spec)
        )

    return jax.tree.map(put, tree, specs)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())
