"""High-level serving assembly.

``serve_worker`` = engine + endpoint + model registration + KV/metrics
publishers (what the reference's engine subprocesses do on startup,
launch/dynamo-run/src/subprocess/*_inc.py); ``serve_frontend`` = HTTP service
+ model watcher (the ``in=http`` frontend, launch/dynamo-run/src/input/http.rs).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path

from dynamo_tpu.llm.discovery import ModelWatcher, register_llm
from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.http import HttpService, ModelManager
from dynamo_tpu.llm.kv_router.publisher import (
    ClearKvListener,
    KvEventPublisher,
    WorkerMetricsPublisher,
)
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.tokenizer import HfTokenizer
from dynamo_tpu.runtime.client import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("serve")


@dataclass
class WorkerHandle:
    service: object
    engine: object
    publishers: list
    _closed: bool = False

    async def shutdown(self) -> None:
        await self.service.shutdown()
        await self._close()

    async def drain(self, timeout_s: float | None = None) -> dict:
        """Gracefully empty this worker: admissions stop at once, in-flight
        requests finish or hand off (resume-redispatch), the lease is
        revoked — then the engine stops.  The scale-down path for planners
        and operators (``dynctl drain`` / SIGTERM) instead of hard kills."""
        result = await self.service.drain(timeout_s)
        await self._close()
        return result

    async def _close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for pub in self.publishers:
            await pub.stop()
        if hasattr(self.engine, "stop"):
            self.engine.stop()


def install_drain_on_sigterm(handle: WorkerHandle, *, timeout_s: float | None = None):
    """Opt-in SIGTERM → graceful drain for CLI launch paths (k8s preStop /
    operator scale-down).  Must run on the main thread of a live event loop
    (``loop.add_signal_handler`` constraint), so library/test embedders call
    ``handle.drain()`` directly instead.  Returns the scheduled drain task
    holder (a one-element list filled when the signal fires)."""
    import asyncio
    import signal

    from dynamo_tpu.utils.tasks import spawn_logged

    loop = asyncio.get_running_loop()
    fired: list = []

    def _on_term() -> None:
        if not fired:
            logger.info("SIGTERM: draining worker before exit")
            fired.append(spawn_logged(handle.drain(timeout_s), name="sigterm-drain"))

    try:
        loop.add_signal_handler(signal.SIGTERM, _on_term)
    except (NotImplementedError, RuntimeError) as exc:
        logger.warning("SIGTERM drain handler unavailable: %r", exc)
    return fired


def build_jax_engine(model_dir: str | Path, mdc: ModelDeploymentCard, **overrides):
    """Build a JaxLlmEngine from a local model dir (config.json; weights from
    safetensors when present, random-init otherwise).

    ``overrides`` pass straight into EngineConfig — notably
    ``decode_overlap`` (the overlapped decode pipeline, default on; the
    ``DYN_DECODE_OVERLAP`` env reaches every launch path through the
    engine itself, so operators can A/B a deployment without code)."""
    import json as _json

    from dynamo_tpu.engine import EngineConfig, JaxLlmEngine
    from dynamo_tpu.models.registry import get_family

    model_dir = Path(model_dir)
    hf_config = _json.loads((model_dir / "config.json").read_text())
    from dynamo_tpu.models.registry import known_families

    model_type = hf_config.get("model_type", "llama")
    family_name = model_type if model_type in known_families() else "llama"
    family = get_family(family_name)
    cfg = family.config_from_hf(hf_config)
    defaults = dict(
        model=cfg,
        model_family=family_name,
        block_size=mdc.kv_block_size,
        num_blocks=overrides.pop("num_blocks", 256),
        max_batch_size=overrides.pop("max_batch_size", 8),
        max_model_len=overrides.pop("max_model_len", mdc.context_length),
    )
    # "warmup" is a launch-time behavior, not an EngineConfig field: pop it
    # here so EVERY launch path (serve_worker, disagg workers, example
    # graphs) can pass it through engine_overrides; callers check
    # ``engine.wants_warmup`` after start()
    wants_warmup = bool(overrides.pop("warmup", False))
    defaults.update(overrides)
    config = EngineConfig(**defaults)
    params = None
    if family.load_weights is not None:
        try:
            params = family.load_weights(cfg, model_dir)
            logger.info("loaded weights from %s", model_dir)
        except FileNotFoundError:
            logger.warning("no safetensors in %s — random-initializing weights", model_dir)
    engine = JaxLlmEngine(config, params=params)
    engine.wants_warmup = wants_warmup
    logger.info(
        "decode pipeline: %s (decode_steps=%d)",
        "overlapped" if engine.decode_overlap else "synchronous",
        config.decode_steps,
    )
    # guided JSON decoding needs the tokenizer-compiled mask table; enable
    # here so EVERY launch path (serve_worker, disagg workers, example
    # graphs) supports response_format json_object.  Best-effort: engines
    # that cannot guide (fused decode, spec) still serve and reject guided
    # requests per-request; a table-build failure serves unguided.
    if config.decode_steps <= 1 and not engine.spec_enabled:
        try:
            engine.enable_guided_json(HfTokenizer.from_model_dir(model_dir))
        except Exception as exc:  # noqa: BLE001 — serving works unguided
            logger.warning("guided-json table build failed: %r", exc)
    return engine


async def serve_worker(
    runtime: DistributedRuntime,
    model_dir: str | Path,
    *,
    model_name: str | None = None,
    namespace: str | None = None,
    component: str = "backend",
    endpoint: str = "generate",
    engine_kind: str = "jax",
    model_types: list[str] | None = None,
    **engine_overrides,
) -> WorkerHandle:
    import asyncio

    from dynamo_tpu.llm.hub import resolve_model

    # snapshot downloads take minutes: never block the event loop (other
    # endpoints/heartbeats on this runtime must keep running)
    model_dir = await asyncio.to_thread(resolve_model, model_dir)
    mdc = ModelDeploymentCard.from_local_path(model_dir, name=model_name)
    ep = runtime.namespace(namespace).component(component).endpoint(endpoint)

    publishers: list = []
    if engine_kind == "echo":
        engine = EchoEngineCore()
        service = await ep.serve(engine)
    elif engine_kind == "mocker":
        from dynamo_tpu.llm.mocker import MockerConfig, MockerEngine

        engine = MockerEngine(MockerConfig(block_size=mdc.kv_block_size))
        service = await ep.serve(engine, stats_handler=engine.stats)
        # mockers exist to exercise routers at scale, so they publish the
        # same KV events + load metrics as the real engine (the mocker's
        # allocator is the real BlockAllocator — its stored/removed events
        # feed the KV router's radix index exactly like serving traffic).
        # Same wiring order as the jax branch: sink attached BEFORE the
        # engine loop starts, so no early request's events are dropped.
        kv_pub = KvEventPublisher(ep.component, worker_id=service.instance.instance_id)
        kv_pub.start()
        engine._event_sink = kv_pub.sink
        metrics_pub = WorkerMetricsPublisher(
            ep.component, service.instance.instance_id, engine.stats
        )
        metrics_pub.start()
        publishers = [kv_pub, metrics_pub]
        engine.start()
    elif engine_kind == "jax":
        # device-plane profiling hooks: DYN_PROFILER_PORT serves the jax
        # profiler for TensorBoard/xprof attach; DYN_PROFILER_TRACE_DIR is
        # honored by engine.start() (a whole-serve-window device trace)
        from dynamo_tpu.utils import profiling

        profiling.maybe_start_from_env()
        # publishers are wired before the engine so allocator events flow.
        # Built off the event loop: weight loading takes seconds and a G4
        # remote tier's mount does blocking TCP (RemoteStorage info RPC) —
        # heartbeats/endpoints on this loop must keep running meanwhile.
        engine = await asyncio.to_thread(
            build_jax_engine, model_dir, mdc, **engine_overrides
        )
        do_warmup = engine.wants_warmup
        service = await ep.serve(engine, stats_handler=engine.stats)
        kv_pub = KvEventPublisher(ep.component, worker_id=service.instance.instance_id)
        kv_pub.start()
        engine._event_sink = kv_pub.sink
        metrics_pub = WorkerMetricsPublisher(
            ep.component, service.instance.instance_id, engine.stats
        )
        metrics_pub.start()
        clear_listener = ClearKvListener(ep.component, engine)
        clear_listener.start()
        publishers = [kv_pub, metrics_pub, clear_listener]
        if getattr(engine, "prefetch_pager", None) is not None:
            from dynamo_tpu.prefetch.worker import PrefetchListener

            prefetch_listener = PrefetchListener(
                ep.component, engine, service.instance.instance_id
            )
            prefetch_listener.start()
            publishers.append(prefetch_listener)
        engine.start()
        if do_warmup:
            # compile every serving program before the model registers:
            # the first user request must not pay cold-start compiles
            await engine.warmup()
    else:
        raise ValueError(f"unknown engine kind {engine_kind!r}")

    await register_llm(service, mdc, model_types=model_types)
    return WorkerHandle(service=service, engine=engine, publishers=publishers)


async def serve_frontend(
    runtime: DistributedRuntime,
    *,
    host: str = "0.0.0.0",
    port: int = 8080,
    router_mode: RouterMode = RouterMode.ROUND_ROBIN,
    request_template: str | Path | None = None,
    admission=None,
) -> tuple[HttpService, ModelWatcher]:
    from dynamo_tpu.llm.request_template import RequestTemplate

    template = RequestTemplate.load(request_template) if request_template else None
    manager = ModelManager()
    # arrival-hint source for predictive prefetch: only meaningful when a
    # KV router is in the path (it owns the radix index that targets the
    # hint), gated by DYN_PREFETCH like the rest of the subsystem
    hinter = None
    if router_mode == RouterMode.KV:
        from dynamo_tpu.prefetch.frontend import FrontendHinter
        from dynamo_tpu.prefetch.hints import prefetch_enabled

        if prefetch_enabled():
            hinter = FrontendHinter()
    watcher = ModelWatcher(
        runtime, manager, router_mode=router_mode, prefetch_hinter=hinter
    )
    service = HttpService(
        manager, host=host, port=port, request_template=template,
        clear_kv=watcher.clear_kv_blocks, admission=admission,
        prefetch_hinter=hinter,
    )
    await watcher.start()
    # same live map on the scrape surface: dyn_topology_* next to dyn_llm_*
    if watcher.topology is not None:
        service.metrics.attach_topology(watcher.topology)
    await service.start()
    return service, watcher
