"""Frontend hint source: announce a request the moment it enters the
admission path, before dispatch.

The HTTP frontend sees the request *earliest* — before preprocessing,
queueing and routing — so its hint gives the pager the whole
admission+dispatch window to page the prefix up-tier.  The frontend
itself holds no tokenizer; the ModelWatcher registers one per model as it
builds the pipeline (the same tokenizer the preprocessor uses, so the
hint's hash chain matches the engine's allocator exactly).

Emission is strictly fire-and-forget: tokenize + hash runs on the event
loop (sub-ms for chat-sized prompts), the bus publish is a background
task, and no failure may surface into request handling.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from dynamo_tpu.llm.kv_router.hashing import compute_block_hashes
from dynamo_tpu.prefetch.hints import SOURCE_ARRIVAL, PrefetchHint
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("prefetch.frontend")


class FrontendHinter:
    """Per-model arrival-hint emitters, registered by the ModelWatcher."""

    def __init__(self) -> None:
        # model name -> (tokenize(request_model) -> list[int] | None,
        #               block_size, async publish(bytes))
        self._models: dict[str, tuple[Callable, int, Callable]] = {}
        self.hints_emitted = 0
        self.hints_skipped = 0

    def register_model(
        self, name: str, tokenize: Callable, block_size: int, publish: Callable
    ) -> None:
        self._models[name] = (tokenize, block_size, publish)

    def remove_model(self, name: str) -> None:
        self._models.pop(name, None)

    def on_request(self, model: str, request_model) -> None:
        """Called by the HTTP handlers right after validation (the request
        has entered admission; dispatch has not started).  Tokenize+hash
        runs synchronously HERE — the hint's entire value is leaving
        before the dispatch path starts (deferring it to a thread loses
        the race against the request's own preprocessing, measured live) —
        and stays bounded because the registered tokenize callbacks cap
        the rendered text at DYN_PREFETCH_HINT_CHARS.  Only the bus
        publish is deferred."""
        entry = self._models.get(model)
        if entry is None:
            return
        tokenize, block_size, publish = entry
        try:
            token_ids = tokenize(request_model)
            hashes = compute_block_hashes(token_ids or [], block_size)
        except Exception:  # noqa: BLE001 — a hint must never fail a request
            logger.debug("prefetch hint tokenization failed", exc_info=True)
            hashes = []
        if not hashes:
            self.hints_skipped += 1
            return
        self.hints_emitted += 1
        hint = PrefetchHint(block_hashes=hashes, source=SOURCE_ARRIVAL)

        async def _publish() -> None:
            try:
                await publish(hint.to_json())
            except Exception:  # noqa: BLE001
                logger.debug("prefetch hint publish failed", exc_info=True)

        spawn_logged(_publish())
