"""Predictive KV prefetch over the offload tiers.

Turns offload paging from reactive to predictive (PRESERVE, arxiv
2501.08192; packing-prefetch scheduling, arxiv 2508.08457): hint sources
announce soon-to-arrive prefixes, the kv_router targets the worker whose
radix index holds the prefix, and the engine's pager onboards the hinted
blocks disk/host→HBM *while the current batch computes* — so a returning
multi-turn session's page-in latency is hidden instead of paid on TTFT.

Pieces (each usable alone):

- :mod:`hints`     — wire protocol + subjects + the ``DYN_PREFETCH`` gate
- :mod:`session`   — SessionPredictor: inter-turn-gap model over prefix
  hash chains, predicting next-turn arrivals
- :mod:`frontend`  — FrontendHinter: emits an arrival hint the moment a
  request enters the HTTP admission path, before dispatch
- :mod:`forwarder` — PrefetchForwarder: router-side targeting (radix
  overlap → worker) + predicted-hint firing
- :mod:`worker`    — PrefetchListener: worker-side subscriber feeding the
  engine's pager
- :mod:`pager`     — PrefetchPager: the engine's priority-ordered job
  queue with stale cancellation and hit/miss/hidden-latency accounting
"""

from dynamo_tpu.prefetch.hints import (
    PREFETCH_HINT_SUBJECT,
    PREFETCH_TARGET_SUBJECT,
    PrefetchHint,
    TargetedPrefetchHint,
    prefetch_enabled,
)
from dynamo_tpu.prefetch.pager import PrefetchPager
from dynamo_tpu.prefetch.session import SessionPredictor

__all__ = [
    "PREFETCH_HINT_SUBJECT",
    "PREFETCH_TARGET_SUBJECT",
    "PrefetchHint",
    "TargetedPrefetchHint",
    "PrefetchPager",
    "SessionPredictor",
    "prefetch_enabled",
]
