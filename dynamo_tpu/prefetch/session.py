"""Session-aware next-turn prediction.

A multi-turn session is identified by its prefix hash chain: turn N+1's
prompt embeds turn N's whole history, so turn N's final block hash appears
*verbatim* inside turn N+1's chain (hashes chain their parents — a hash is
the whole prefix ending at that block).  That makes session tracking
tokenizer- and content-free: observe each request's chain, match it to the
session whose recorded tip it contains, and model the inter-turn gap.

The gap model is an EWMA over observed think times (PRESERVE, arxiv
2501.08192 models returning-session arrival the same way).  A predicted
arrival fires once per turn, ``lead_s`` before the expected time, giving
the pager that long to page the session's blocks up-tier.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field


@dataclass
class _Session:
    tip: int                         # last block hash of the latest turn's chain
    hashes: list[int]                # the latest turn's full chain
    last_arrival: float
    gap_ewma: float | None = None    # seconds between consecutive turns
    fired: bool = False              # predicted hint already emitted for next turn
    turns: int = 1


@dataclass
class Prediction:
    block_hashes: list[int] = field(default_factory=list)
    predicted_at: float = 0.0        # expected arrival time


class SessionPredictor:
    """Tracks sessions by prefix hash chain and predicts next-turn arrivals.

    Single-consumer (the forwarder's event loop); bounded to
    ``max_sessions`` by LRU on last arrival.
    """

    def __init__(
        self,
        *,
        lead_s: float = 1.0,
        alpha: float = 0.5,
        min_gap_s: float = 0.05,
        max_sessions: int = 4096,
        clock=time.monotonic,
    ):
        self.lead_s = lead_s
        self.alpha = alpha
        self.min_gap_s = min_gap_s
        self.max_sessions = max_sessions
        self._clock = clock
        # tip hash -> session (a session is re-keyed to its new tip each turn)
        self._sessions: OrderedDict[int, _Session] = OrderedDict()
        self.turns_observed = 0
        self.sessions_tracked = 0

    def observe(self, block_hashes: list[int], now: float | None = None) -> bool:
        """Record an arrival.  Returns True when it continued a known
        session (and the gap model updated)."""
        if not block_hashes:
            return False
        now = self._clock() if now is None else now
        self.turns_observed += 1
        # walk the chain from the END: the longest (newest) matching tip wins
        # when one session's history embeds another's
        matched = None
        for h in reversed(block_hashes):
            sess = self._sessions.get(h)
            if sess is not None:
                matched = (h, sess)
                break
        tip = block_hashes[-1]
        if matched is None:
            self._sessions[tip] = _Session(
                tip=tip, hashes=list(block_hashes), last_arrival=now
            )
            self._sessions.move_to_end(tip)
            self.sessions_tracked += 1
            self._evict()
            return False
        old_tip, sess = matched
        gap = max(now - sess.last_arrival, self.min_gap_s)
        sess.gap_ewma = (
            gap if sess.gap_ewma is None
            else self.alpha * gap + (1.0 - self.alpha) * sess.gap_ewma
        )
        sess.last_arrival = now
        sess.hashes = list(block_hashes)
        sess.fired = False
        sess.turns += 1
        if old_tip != tip:
            del self._sessions[old_tip]
            sess.tip = tip
            self._sessions[tip] = sess
        self._sessions.move_to_end(tip)
        self._evict()
        return True

    def due(self, now: float | None = None) -> list[Prediction]:
        """Predictions whose fire time (expected arrival − lead) has come.
        Each next-turn prediction fires exactly once."""
        now = self._clock() if now is None else now
        out: list[Prediction] = []
        for sess in self._sessions.values():
            if sess.fired or sess.gap_ewma is None:
                continue
            expected = sess.last_arrival + sess.gap_ewma
            if now >= expected - self.lead_s:
                sess.fired = True
                out.append(
                    Prediction(block_hashes=list(sess.hashes), predicted_at=expected)
                )
        return out

    def _evict(self) -> None:
        while len(self._sessions) > self.max_sessions:
            self._sessions.popitem(last=False)

    def __len__(self) -> int:
        return len(self._sessions)
