"""Router-side hint targeting + next-turn prediction.

Subscribes the component's ``prefetch_hints`` subject, resolves each hint
to the worker whose radix index holds the longest matching prefix (the
index covers every tier the worker still has the content in — ``removed``
only fires when a hash leaves the worker's *bottom* tier), and republishes
on ``prefetch_targets`` for that worker's listener.

Arrival hints also feed the :class:`SessionPredictor`; a periodic task
fires predicted next-turn hints through the same targeting path, so a
parked session's blocks start paging up-tier *before* the user returns.
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.prefetch.hints import (
    PREFETCH_HINT_SUBJECT,
    PREFETCH_TARGET_SUBJECT,
    SOURCE_PREDICTED,
    PrefetchHint,
    TargetedPrefetchHint,
)
from dynamo_tpu.prefetch.session import SessionPredictor
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("prefetch.forwarder")


class PrefetchForwarder:
    """Owns the hint subscription + prediction loop for one component."""

    def __init__(
        self,
        component,
        indexer,
        *,
        predictor: SessionPredictor | None = None,
        predict_period_s: float = 0.25,
        min_overlap_blocks: int = 1,
    ):
        self.component = component
        self.indexer = indexer
        self.predictor = predictor or SessionPredictor()
        self.predict_period_s = predict_period_s
        self.min_overlap_blocks = min_overlap_blocks
        self._sub = None
        self._tasks: list[asyncio.Task] = []
        self.forwarded_total = 0
        self.unroutable_total = 0
        self.predicted_total = 0

    async def start(self) -> None:
        # initial subscribe happens HERE (not in the loop task) so a hint
        # published right after start() cannot race the subscription
        bus = self.component.runtime.plane.bus
        self._sub = await bus.subscribe(
            self.component.event_subject(PREFETCH_HINT_SUBJECT)
        )
        self._tasks = [
            spawn_logged(self._hint_loop()),
            spawn_logged(self._predict_loop()),
        ]

    async def stop(self) -> None:
        # cancel before unsubscribing so the loop can't resubscribe in
        # the window between the two
        for task in self._tasks:
            task.cancel()
        self._tasks = []
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None

    # -- loops ---------------------------------------------------------------
    async def _hint_loop(self) -> None:
        # resubscribe-on-failure (same shape as the worker's
        # PrefetchListener): a control-plane blip must not silently kill
        # hint targeting for the component's remaining lifetime
        bus = self.component.runtime.plane.bus
        subject = self.component.event_subject(PREFETCH_HINT_SUBJECT)
        while True:
            try:
                if self._sub is None:
                    self._sub = await bus.subscribe(subject)
                async for msg in self._sub:
                    # one malformed hint (or indexer hiccup) must not kill
                    # targeting — catch everything per message
                    try:
                        hint = PrefetchHint.from_json(msg.payload)
                        self.predictor.observe(hint.block_hashes)
                        await self._target(hint)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001
                        logger.exception("prefetch hint handling failed")
                self._sub = None  # iterator ended cleanly: fresh subscribe
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("prefetch hint subscription lost; retrying")
                self._sub = None
            await asyncio.sleep(1.0)

    async def _predict_loop(self) -> None:
        while True:
            await asyncio.sleep(self.predict_period_s)
            try:
                for pred in self.predictor.due():
                    self.predicted_total += 1
                    await self._target(
                        PrefetchHint(
                            block_hashes=pred.block_hashes,
                            source=SOURCE_PREDICTED,
                        )
                    )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("prefetch prediction failed")

    # -- targeting -----------------------------------------------------------
    async def _target(self, hint: PrefetchHint) -> None:
        """Forward to the worker with the deepest prefix overlap.  No
        overlap anywhere ⇒ no worker holds the content in any tier —
        nothing to page in, drop the hint."""
        overlap = self.indexer.find_matches(hint.block_hashes)
        if not overlap.scores:
            self.unroutable_total += 1
            return
        worker_id, blocks = max(overlap.scores.items(), key=lambda kv: kv[1])
        if blocks < self.min_overlap_blocks:
            self.unroutable_total += 1
            return
        self.forwarded_total += 1
        try:
            await self.component.runtime.plane.bus.publish(
                self.component.event_subject(PREFETCH_TARGET_SUBJECT),
                TargetedPrefetchHint(worker_id=worker_id, hint=hint).to_json(),
            )
        except Exception:  # noqa: BLE001 — hints are best-effort
            logger.debug("prefetch target publish failed", exc_info=True)
