"""Worker-side prefetch hint listener.

Subscribes the component's ``prefetch_targets`` subject (same
resubscribe-on-failure shape as ``ClearKvListener``), filters messages
addressed to this worker's instance id, and feeds the engine's pager via
``engine.prefetch_hint`` — a thread-safe enqueue that wakes the device
loop."""

from __future__ import annotations

import asyncio

from dynamo_tpu.prefetch.hints import PREFETCH_TARGET_SUBJECT, TargetedPrefetchHint
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("prefetch.worker")


class PrefetchListener:
    def __init__(self, component, engine, worker_id: int):
        self.component = component
        self.engine = engine
        self.worker_id = worker_id
        self.subject = component.event_subject(PREFETCH_TARGET_SUBJECT)
        self._task: asyncio.Task | None = None
        self._sub = None
        self.received_total = 0

    def start(self) -> None:
        if self._task is None:
            self._task = spawn_logged(self._loop())

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        bus = self.component.runtime.plane.bus
        while True:
            try:
                self._sub = await bus.subscribe(self.subject)
                async for msg in self._sub:
                    try:
                        targeted = TargetedPrefetchHint.from_json(msg.payload)
                    except Exception:  # noqa: BLE001
                        logger.exception("bad targeted prefetch hint")
                        continue
                    if targeted.worker_id != self.worker_id:
                        continue
                    self.received_total += 1
                    try:
                        self.engine.prefetch_hint(
                            targeted.hint.block_hashes, source=targeted.hint.source
                        )
                    except Exception:  # noqa: BLE001 — hints are best-effort
                        logger.exception("prefetch hint rejected by engine")
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("prefetch listener lost its subscription; retrying")
            await asyncio.sleep(1.0)
