"""Prefetch hint wire protocol.

Hints ride the control-plane bus on component-scoped event subjects, the
same transport as KV events (``kv_router/protocols.py``):

- ``prefetch_hints``   — hint sources (frontend arrival hints, predicted
  next-turn hints) → the router's forwarder
- ``prefetch_targets`` — forwarder → workers, attributed to the worker
  whose radix index showed prefix overlap (every worker of the component
  receives the message and filters on its own id — subjects are
  component-scoped, exactly like ``clear_kv_blocks``)

A hint carries block *hashes*, not tokens: hashes are the cross-layer
currency (allocator registry, radix index, offload tiers all key on the
same chained xxh3), and a hint must never carry prompt content over the
bus.
"""

from __future__ import annotations

import json
import os
import time
from dynamo_tpu.utils import knobs
from dataclasses import asdict, dataclass, field

PREFETCH_HINT_SUBJECT = "prefetch_hints"
PREFETCH_TARGET_SUBJECT = "prefetch_targets"

# hint sources, in descending urgency: a request already queued on this
# worker > a request entering the frontend's admission path > a predicted
# next-turn arrival
SOURCE_QUEUED = "queued"
SOURCE_ARRIVAL = "arrival"
SOURCE_PREDICTED = "predicted"

# smaller = sooner in the pager's priority queue
SOURCE_PRIORITY = {SOURCE_QUEUED: 0, SOURCE_ARRIVAL: 10, SOURCE_PREDICTED: 20}


def prefetch_enabled(default: bool = True) -> bool:
    """The ``DYN_PREFETCH`` gate (0/false/off disables; default on).
    ``DYN_PREFETCH=0`` restores fully demand-driven paging everywhere."""
    value = knobs.get_raw("DYN_PREFETCH")
    if value is None:
        return default
    return knobs.parse_bool(value, default)


@dataclass
class PrefetchHint:
    """A prefix expected to be requested soon."""

    block_hashes: list[int] = field(default_factory=list)
    source: str = SOURCE_ARRIVAL
    ts: float = field(default_factory=time.time)

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_dict(cls, d: dict) -> "PrefetchHint":
        """Unknown keys dropped: a newer peer may add fields, and an older
        listener must keep decoding (same contract for nested hints)."""
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_json(cls, data: bytes) -> "PrefetchHint":
        return cls.from_dict(json.loads(data))


@dataclass
class TargetedPrefetchHint:
    """A hint resolved to the worker holding the offloaded prefix."""

    worker_id: int
    hint: PrefetchHint

    def to_json(self) -> bytes:
        return json.dumps(
            {"worker_id": self.worker_id, "hint": asdict(self.hint)}
        ).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "TargetedPrefetchHint":
        d = json.loads(data)
        return cls(worker_id=d["worker_id"], hint=PrefetchHint.from_dict(d["hint"]))
