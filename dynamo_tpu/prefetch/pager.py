"""PrefetchPager: the engine-side prefetch job queue + accounting.

A priority-ordered queue of hinted prefixes, drained by the engine's
device loop between steps (bounded blocks per iteration, so prefetch can
never stall serving).  Jobs older than ``ttl_s`` are cancelled as stale —
a hint whose request never materialized must not keep paging.

Accounting answers "did prefetch buy anything":

- **hit**: a prefetched block was matched by a real sequence before
  leaving HBM — its recorded page-in cost is credited to
  ``hidden_seconds`` (latency removed from that request's critical path).
- **miss**: a prefetched block was evicted from HBM (or its sequence
  freed it unconsumed) before any hit — wasted page-in work.
- **stale**: a job expired before the pager ran it.

Thread model: ``submit`` is called from the asyncio thread (bus listener)
and the device thread (queue self-hints); everything else runs on the
device thread.  The allocator calls ``on_block_hit``/``on_block_evicted``
under its own lock, so this class keeps its own small lock and never
calls back out.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field

from dynamo_tpu.prefetch.hints import SOURCE_PRIORITY
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("prefetch.pager")

# per-hash cost memory: bounded — oldest entries beyond this are treated
# as already-judged (they count as misses when forgotten unconsumed)
MAX_TRACKED_BLOCKS = 65536

# link-class pricing for tier page-ins (topology plane): a page-in whose
# backing tier sits behind a slower hop gets a smaller per-step budget —
# the device loop must not stall serving while blocks crawl over DCN.
# Fractions of the configured blocks_per_step; "" / "local" = full budget.
LINK_BUDGET_FRACTION = {
    "": 1.0,
    "local": 1.0,
    "ici": 0.5,
    "dcn": 0.25,
}


@dataclass(order=True)
class _Job:
    priority: int
    seq: int
    hashes: list[int] = field(compare=False)
    enqueued: float = field(compare=False, default=0.0)


class PrefetchPager:
    def __init__(
        self,
        *,
        ttl_s: float = 30.0,
        blocks_per_step: int = 64,
        idle_boost: int = 4,
        clock=time.monotonic,
    ):
        self.ttl_s = ttl_s
        self.blocks_per_step = blocks_per_step
        self.idle_boost = idle_boost
        # hop class of the link behind the offload tier (set_link_hop):
        # scales the effective per-step page-in budget by LINK_BUDGET_FRACTION
        self.link_hop = ""
        self._clock = clock
        self._lock = threading.Lock()
        self._queue: list[_Job] = []
        self._seq = itertools.count()
        # hashes with a queued job (dedupe: N queued requests for one hot
        # prefix collapse to the first job; re-hint after execution re-queues)
        self._queued_hashes: set[int] = set()
        # hash -> page-in seconds spent bringing it into HBM (judged on
        # hit/evict); insertion-ordered for bounded forgetting
        self._cost: dict[int, float] = {}
        # counters (exported via engine stats → dyn_prefetch_* families)
        self.hints_total = 0
        self.hits_total = 0
        self.misses_total = 0
        self.stale_total = 0
        self.hidden_seconds_total = 0.0
        self.blocks_restored_total = 0   # host tier → HBM pre-restores
        self.blocks_onboarded_total = 0  # disk/remote → host promotions
        self.deferred_total = 0          # jobs postponed for HBM headroom

    # -- link pricing (topology plane) ----------------------------------------
    def set_link_hop(self, hop: str) -> None:
        """Price tier page-ins by the hop class behind the offload tier
        (from the discovered TopologyMap).  Unknown classes price like
        ``dcn`` — assume the worst about an unclassified link."""
        self.link_hop = hop or ""

    def effective_blocks_per_step(self) -> int:
        fraction = LINK_BUDGET_FRACTION.get(
            self.link_hop, LINK_BUDGET_FRACTION["dcn"]
        )
        return max(1, int(self.blocks_per_step * fraction))

    # -- queue (any thread) --------------------------------------------------
    def submit(self, block_hashes: list[int], *, source: str = "arrival") -> bool:
        """Queue a hinted prefix.  Returns False when nothing new to do
        (empty, or every hash already queued).  Only the hashes not
        already queued ride in the job — the queue and ``_queued_hashes``
        must agree exactly, or popping one job would unmark hashes a
        sibling job still carries and let a third hint re-queue them."""
        if not block_hashes:
            return False
        priority = SOURCE_PRIORITY.get(source, 10)
        with self._lock:
            fresh = [h for h in block_hashes if h not in self._queued_hashes]
            if not fresh:
                return False
            self.hints_total += 1
            self._queued_hashes.update(fresh)
            heapq.heappush(
                self._queue,
                _Job(priority, next(self._seq), fresh, self._clock()),
            )
            return True

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._queue)

    def next_job(self) -> _Job | None:
        """Pop the most urgent non-stale job (device thread)."""
        now = self._clock()
        with self._lock:
            while self._queue:
                job = heapq.heappop(self._queue)
                self._queued_hashes.difference_update(job.hashes)
                if now - job.enqueued > self.ttl_s:
                    self.stale_total += 1
                    continue
                return job
            return None

    def requeue(
        self, hashes: list[int], *, enqueued: float | None = None,
        priority: int = 5,
    ) -> None:
        """Put back a job the engine could not finish (HBM headroom): it
        retries ahead of fresh arrival hints and keeps its ORIGINAL
        enqueue time (pass the popped job's ``enqueued``), so a hint that
        keeps deferring still goes stale after ``ttl_s`` instead of being
        re-walked forever while HBM stays saturated."""
        with self._lock:
            fresh = [h for h in hashes if h not in self._queued_hashes]
            if not fresh:
                return
            self.deferred_total += 1
            self._queued_hashes.update(fresh)
            heapq.heappush(
                self._queue,
                _Job(
                    priority, next(self._seq), fresh,
                    self._clock() if enqueued is None else enqueued,
                ),
            )

    # -- accounting (device thread + allocator lock) -------------------------
    def record_restored(self, seq_hash: int, cost_s: float) -> None:
        """A block was pre-restored into HBM at this page-in cost."""
        with self._lock:
            self.blocks_restored_total += 1
            self._cost[seq_hash] = cost_s
            while len(self._cost) > MAX_TRACKED_BLOCKS:
                # forgotten unconsumed = it never hit: judge it a miss
                self._cost.pop(next(iter(self._cost)))
                self.misses_total += 1

    def record_onboarded(self, n: int) -> None:
        with self._lock:
            self.blocks_onboarded_total += n

    def on_block_hit(self, seq_hash: int) -> None:
        """Allocator hook: a sequence matched a prefetched device block."""
        with self._lock:
            cost = self._cost.pop(seq_hash, None)
            if cost is None:
                return
            self.hits_total += 1
            self.hidden_seconds_total += cost

    def on_block_evicted(self, seq_hash: int) -> None:
        """Allocator hook: a prefetched block left HBM before any hit."""
        with self._lock:
            if self._cost.pop(seq_hash, None) is not None:
                self.misses_total += 1

    def is_tracked(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._cost

    def stats(self) -> dict:
        with self._lock:
            return {
                "prefetch_hints_total": self.hints_total,
                "prefetch_hits_total": self.hits_total,
                "prefetch_misses_total": self.misses_total,
                "prefetch_stale_total": self.stale_total,
                "prefetch_hidden_seconds_total": round(self.hidden_seconds_total, 6),
                "prefetch_blocks_restored_total": self.blocks_restored_total,
                "prefetch_blocks_onboarded_total": self.blocks_onboarded_total,
                "prefetch_deferred_total": self.deferred_total,
                "prefetch_queue_depth": len(self._queue),
                "prefetch_blocks_per_step_effective": self.effective_blocks_per_step(),
            }
