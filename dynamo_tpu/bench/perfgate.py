"""Perf regression gate: a dynlint-style ratchet over the committed perf
artifacts.

The repo commits a pile of benchmark artifacts (PROFILE_DECODE.json,
DISAGG_BENCH.json, SCENARIO_SOAK.json, KERNEL_PERF.json,
PREFETCH_BENCH.json, MIGRATION_BENCH.json) but, before this gate, nothing
diffed them across PRs — a perf regression was silent while a lint finding
failed tier-1.  This module is the missing ratchet, modeled exactly on
``scripts/dynlint.py`` + ``ANALYSIS_BASELINE.json``:

- a canonical metric-extraction schema (:data:`METRICS`) names the headline
  number(s) in each artifact, its direction, and its tolerance band;
- ``PERF_BASELINE.json`` commits the accepted values;
- a NEW regression (metric degraded beyond its band vs baseline) FAILS;
- a STALE baseline entry (metric no longer extractable / no longer in the
  schema) FAILS — the baseline must be regenerated, never hand-edited;
- an artifact whose provenance header names a different schema generation
  is refused (its metrics are excluded from both checks) instead of being
  diffed as garbage;
- ``scripts/perfgate.py --write-baseline`` re-records legitimately — and
  refuses to run over a dirty artifact set.

Wired into tier-1 via ``tests/bench/test_perf_gate.py``.  Pure stdlib on
purpose: the gate must run without JAX.
"""

from __future__ import annotations

import json
import os
import subprocess
from dataclasses import dataclass
from pathlib import Path

# Bumped when the meaning of extracted metrics changes incompatibly —
# artifacts stamped with a DIFFERENT generation are refused, not diffed.
PERFGATE_SCHEMA_VERSION = 1

BASELINE_NAME = "PERF_BASELINE.json"

ARTIFACTS = (
    "PROFILE_DECODE.json",
    "DISAGG_BENCH.json",
    "SCENARIO_SOAK.json",
    "KERNEL_PERF.json",
    "PREFETCH_BENCH.json",
    "MIGRATION_BENCH.json",
)


@dataclass(frozen=True)
class MetricSpec:
    """One ratcheted metric: where it lives, which way is better, and how
    much drift the band forgives.

    ``path`` is a dot path into the artifact JSON.  A ``max:`` prefix folds
    a list: ``max:rows[].tflops`` is the max of ``row["tflops"]`` over
    ``rows``.  Booleans extract as 0/1 so "must stay true" is just a
    higher-direction metric with a zero band.
    """

    name: str           # stable metric id (baseline key)
    artifact: str       # which committed file it comes from
    path: str           # extraction path (see above)
    direction: str      # "higher" | "lower" — which way is BETTER
    rel_tol: float      # relative drift forgiven before a regression fires
    abs_slack: float = 0.0  # additive slack (for near-zero baselines)
    doc: str = ""


METRICS: tuple[MetricSpec, ...] = (
    # -- decode-loop A/B (scripts/profile_decode.py) -------------------------
    MetricSpec(
        "profile_decode.overlap_speedup_steps_s", "PROFILE_DECODE.json",
        "overlap_speedup_steps_s", "higher", 0.10,
        doc="overlapped vs sync decode step cadence (seed-artifact geometry)"),
    MetricSpec(
        "profile_decode.tiny_overlap_speedup_tok_s", "PROFILE_DECODE.json",
        "tiny_ab.overlap_speedup_tok_s", "higher", 0.10,
        doc="overlapped vs sync token throughput on the tiny-model A/B"),
    MetricSpec(
        "profile_decode.unified_speedup_steps_s", "PROFILE_DECODE.json",
        "mixed.unified_speedup_steps_s", "higher", 0.10,
        doc="unified-batch vs split decode-step cadence (mixed stream)"),
    MetricSpec(
        "profile_decode.unified_admission_drains", "PROFILE_DECODE.json",
        "mixed.admission_drains_unified", "lower", 0.0,
        doc="admission-forced pipeline drains under unified batch (stay 0)"),
    # -- disagg streamed KV transfer (scripts/disagg_bench.py) ---------------
    MetricSpec(
        "disagg_bench.streamed_ttft_p50_speedup", "DISAGG_BENCH.json",
        "streamed_ab.ttft_p50_speedup", "higher", 0.15,
        doc="streamed vs single-shot disagg TTFT p50"),
    MetricSpec(
        "disagg_bench.streamed_hidden_fraction", "DISAGG_BENCH.json",
        "streamed_ab.streamed.transfer_hidden_fraction", "higher", 0.15,
        doc="fraction of KV transfer hidden behind prefill compute"),
    MetricSpec(
        "disagg_bench.preferred_is_near", "DISAGG_BENCH.json",
        "fleet.preferred_is_near", "higher", 0.0,
        doc="topology-aware disagg router prefers the near decode worker"),
    # -- scenario soak (scripts/scenario_soak.py) ----------------------------
    MetricSpec(
        "scenario_soak.passed", "SCENARIO_SOAK.json",
        "passed", "higher", 0.0,
        doc="the committed default soak passed every phase assertion"),
    MetricSpec(
        "scenario_soak.worst_burn_rate", "SCENARIO_SOAK.json",
        "slo.worst_burn_rate", "lower", 0.0, abs_slack=0.5,
        doc="worst SLO burn rate observed across the soak"),
    # -- kernels (scripts/bench_kernels.py, compiled on real hardware) -------
    MetricSpec(
        "kernel_perf.max_tflops", "KERNEL_PERF.json",
        "max:rows[].tflops", "higher", 0.25,
        doc="best kernel throughput row (loose band: hardware noise)"),
    # -- predictive prefetch (scripts/prefetch_bench.py) ---------------------
    MetricSpec(
        "prefetch_bench.ttft_p50_speedup", "PREFETCH_BENCH.json",
        "demand_over_prefetch_ttft_p50", "higher", 0.20,
        doc="returning-session TTFT p50, demand over prefetch"),
    MetricSpec(
        "prefetch_bench.prefetch_hits", "PREFETCH_BENCH.json",
        "prefetch.prefetch_hits_total", "higher", 0.10,
        doc="prefetched blocks consumed before eviction"),
    # -- live migration (scripts/migration_bench.py) -------------------------
    MetricSpec(
        "migration_bench.requests_failed", "MIGRATION_BENCH.json",
        "requests.failed", "lower", 0.0,
        doc="failed requests across the migration soak (stay 0)"),
    MetricSpec(
        "migration_bench.byte_identical", "MIGRATION_BENCH.json",
        "byte_identical", "higher", 0.0,
        doc="migrated outputs byte-identical to unmigrated replays"),
    MetricSpec(
        "migration_bench.committed", "MIGRATION_BENCH.json",
        "migrations.committed", "higher", 0.25,
        doc="migrations committed across the soak phases"),
    MetricSpec(
        "migration_bench.defrag_var_drop_ratio", "MIGRATION_BENCH.json",
        "kv_occupancy_variance.kv_occ_var_drop_ratio", "higher", 0.30,
        doc="KV occupancy variance removed by planner defrag"),
)


@dataclass(frozen=True)
class Finding:
    """One gate failure, named like a dynlint finding."""

    kind: str    # "regression" | "stale" | "unbaselined" | "missing-artifact"
                 # | "unreadable-artifact" | "incompatible-artifact"
    metric: str  # metric id, or artifact name for artifact-level findings
    detail: str

    def __str__(self) -> str:
        return f"[{self.kind}] {self.metric}: {self.detail}"


# -- provenance --------------------------------------------------------------


def provenance_stamp() -> dict:
    """The shared provenance header artifact writers embed (under the
    ``provenance`` key) so the gate can refuse to diff incompatible
    artifact generations.  Host class comes from the knob override, else
    the JAX default backend; git describe is passed via env by CI."""
    from dynamo_tpu.utils import knobs

    host_class = knobs.get(knobs.K_PERFGATE_HOST_CLASS)
    if not host_class:
        try:
            import jax

            host_class = jax.default_backend()
        except Exception:  # noqa: BLE001 — the stamp must work without JAX
            host_class = "unknown"
    return {
        "schema_version": PERFGATE_SCHEMA_VERSION,
        "git_describe": knobs.get(knobs.K_PERFGATE_GIT_DESCRIBE) or "",
        "host_class": host_class,
    }


def provenance_finding(artifact: str, data: dict) -> Finding | None:
    """A finding iff the artifact carries a provenance header from a
    DIFFERENT schema generation.  Artifacts without a header predate the
    provenance stamp and are accepted as the current generation."""
    prov = data.get("provenance")
    if not isinstance(prov, dict):
        return None
    version = prov.get("schema_version")
    if version != PERFGATE_SCHEMA_VERSION:
        return Finding(
            "incompatible-artifact", artifact,
            f"provenance schema_version={version!r} but this gate speaks "
            f"{PERFGATE_SCHEMA_VERSION}; regenerate the artifact",
        )
    return None


# -- extraction --------------------------------------------------------------


def _extract_path(data, path: str):
    """Value at a dot path; ``max:`` folds a ``seg[]`` list segment."""
    fold = None
    if path.startswith("max:"):
        fold, path = max, path[4:]
    node = data
    for seg in path.split("."):
        if seg.endswith("[]"):
            if isinstance(node, dict):
                node = node.get(seg[:-2])
            if not isinstance(node, list):
                return None
            continue
        if isinstance(node, list):
            node = [item.get(seg) for item in node
                    if isinstance(item, dict) and item.get(seg) is not None]
        elif isinstance(node, dict):
            node = node.get(seg)
        else:
            return None
        if node is None:
            return None
    if isinstance(node, list):
        if fold is None or not node:
            return None
        return fold(node)
    if fold is not None:
        return None
    return node


def _as_number(value) -> float | None:
    if isinstance(value, bool):
        return 1.0 if value else 0.0
    if isinstance(value, (int, float)):
        return float(value)
    return None


def extract_metrics(root: str | os.PathLike) -> tuple[dict, list[Finding]]:
    """(metric id → value) over every readable, compatible artifact under
    ``root``, plus artifact-level findings (missing / unreadable /
    incompatible).  Metrics of refused artifacts are absent from the value
    map AND recorded in the second element of the return so callers can
    exclude them from stale checks."""
    root = Path(root)
    values: dict[str, float] = {}
    findings: list[Finding] = []
    refused: set[str] = set()
    loaded: dict[str, dict] = {}
    for artifact in ARTIFACTS:
        path = root / artifact
        if not path.exists():
            findings.append(Finding(
                "missing-artifact", artifact, f"{path} does not exist"))
            refused.add(artifact)
            continue
        try:
            data = json.loads(path.read_text())
        except (OSError, ValueError) as exc:
            findings.append(Finding(
                "unreadable-artifact", artifact, f"{path}: {exc}"))
            refused.add(artifact)
            continue
        bad = provenance_finding(artifact, data)
        if bad is not None:
            findings.append(bad)
            refused.add(artifact)
            continue
        loaded[artifact] = data
    for spec in METRICS:
        if spec.artifact in refused:
            continue
        value = _as_number(_extract_path(loaded[spec.artifact], spec.path))
        if value is not None:
            values[spec.name] = value
    return values, findings


def refused_artifacts(findings: list[Finding]) -> set[str]:
    return {
        f.metric for f in findings
        if f.kind in ("missing-artifact", "unreadable-artifact",
                      "incompatible-artifact")
    }


# -- baseline ----------------------------------------------------------------


def baseline_path(root: str | os.PathLike) -> Path:
    from dynamo_tpu.utils import knobs

    explicit = knobs.get(knobs.K_PERFGATE_BASELINE)
    if explicit:
        return Path(explicit)
    return Path(root) / BASELINE_NAME


def load_baseline(path: str | os.PathLike) -> dict:
    data = json.loads(Path(path).read_text())
    if not isinstance(data.get("metrics"), dict):
        raise ValueError(f"{path}: no 'metrics' map (not a perf baseline?)")
    return data


def write_baseline(root: str | os.PathLike,
                   path: str | os.PathLike | None = None,
                   note: str | None = None) -> Path:
    """Re-record the baseline from the current artifact pile.  Refuses when
    any artifact is missing/unreadable/incompatible — a baseline must only
    ever be written over a clean, current pile."""
    values, findings = extract_metrics(root)
    if findings:
        raise ValueError(
            "refusing to write a baseline over a broken artifact pile:\n"
            + "\n".join(str(f) for f in findings)
        )
    out = Path(path) if path is not None else baseline_path(root)
    payload = {
        "version": 1,
        "schema_version": PERFGATE_SCHEMA_VERSION,
        "note": note or (
            "Perf-gate ratchet over the committed benchmark artifacts. "
            "Regenerate with scripts/perfgate.py --write-baseline after a "
            "LEGITIMATE perf change (see docs/autopilot.md) — never "
            "hand-edit."
        ),
        "metrics": {name: values[name] for name in sorted(values)},
    }
    out.write_text(json.dumps(payload, indent=2) + "\n")
    return out


def dirty_artifacts(root: str | os.PathLike) -> list[str]:
    """Artifact files with uncommitted modifications per git — the
    --write-baseline refusal: a baseline recorded over a dirty pile would
    launder unreviewed numbers into the ratchet."""
    try:
        proc = subprocess.run(
            ["git", "status", "--porcelain", "--", *ARTIFACTS, BASELINE_NAME],
            cwd=str(root), capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return []
    if proc.returncode != 0:
        return []  # not a git checkout: nothing to refuse on
    dirty = []
    for line in proc.stdout.splitlines():
        name = line[3:].strip()
        if name and name != BASELINE_NAME:
            dirty.append(name)
    return sorted(set(dirty))


# -- the gate ----------------------------------------------------------------


def _band_ok(spec: MetricSpec, value: float, base: float) -> bool:
    if spec.direction == "higher":
        floor = base * (1.0 - spec.rel_tol) - spec.abs_slack
        return value >= floor
    ceiling = base * (1.0 + spec.rel_tol) + spec.abs_slack
    return value <= ceiling


def check(root: str | os.PathLike,
          baseline: dict | None = None) -> list[Finding]:
    """All gate findings for the artifact pile under ``root`` (repo root in
    tier-1).  Empty list = gate passes."""
    root = Path(root)
    if baseline is None:
        baseline = load_baseline(baseline_path(root))
    values, findings = extract_metrics(root)
    refused = refused_artifacts(findings)
    specs = {spec.name: spec for spec in METRICS}
    base_metrics = baseline.get("metrics", {})
    for name, base in sorted(base_metrics.items()):
        spec = specs.get(name)
        if spec is None:
            findings.append(Finding(
                "stale", name,
                "baseline entry is not in the metric schema anymore; "
                "regenerate with scripts/perfgate.py --write-baseline"))
            continue
        if spec.artifact in refused:
            continue  # already failed artifact-level; don't double-report
        value = values.get(name)
        if value is None:
            findings.append(Finding(
                "stale", name,
                f"baseline entry no longer extractable from {spec.artifact} "
                f"(path {spec.path!r}); regenerate the baseline"))
            continue
        base_num = _as_number(base)
        if base_num is None:
            findings.append(Finding(
                "stale", name, f"baseline value {base!r} is not numeric"))
            continue
        if not _band_ok(spec, value, base_num):
            findings.append(Finding(
                "regression", name,
                f"{spec.artifact}:{spec.path} = {value:g}, baseline "
                f"{base_num:g}, direction={spec.direction} "
                f"rel_tol={spec.rel_tol:g} abs_slack={spec.abs_slack:g} "
                f"({spec.doc})"))
    for name in sorted(values):
        if name not in base_metrics and specs[name].artifact not in refused:
            findings.append(Finding(
                "unbaselined", name,
                "metric extracted but absent from the baseline; record it "
                "with scripts/perfgate.py --write-baseline"))
    return findings
