"""Concurrency sweep harness (the genai-perf analog; reference:
benchmarks/llm/perf.sh concurrency 1,2,4,…,256 + plot_pareto.py).

Drives an engine (direct wire-dict interface or HTTP) at fixed concurrency
levels, measuring per-level: output tok/s (total and per-user), request
throughput, TTFT p50/p99, ITL mean.  Results feed the Pareto of
tok/s/user vs tok/s/chip.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import asdict, dataclass, field
from typing import Sequence

from dynamo_tpu.llm.protocols.common import (
    Annotated,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context


@dataclass
class SweepPoint:
    concurrency: int
    requests: int
    wall_s: float
    output_tokens: int
    tok_s_total: float          # tok/s/chip at 1 chip
    tok_s_per_user: float
    req_s: float
    ttft_p50_ms: float
    ttft_p99_ms: float
    itl_mean_ms: float


@dataclass
class SweepConfig:
    concurrencies: Sequence[int] = (1, 2, 4, 8, 16, 32)
    requests_per_level: int = 32
    isl: int = 128
    osl: int = 64
    vocab_size: int = 32_000
    seed: int = 0


async def _drive_one(engine, token_ids: list[int], osl: int) -> tuple[int, float, list[float]]:
    request = PreprocessedRequest(
        token_ids=token_ids,
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=osl, ignore_eos=True),
    ).to_wire()
    t0 = time.monotonic()
    stamps: list[float] = []
    count = 0
    stream = await engine.generate(Context(request))
    async for item in stream:
        ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
        if ann.data is not None and ann.data.token_ids:
            stamps.append(time.monotonic() - t0)
            count += len(ann.data.token_ids)
    return count, stamps[0] if stamps else 0.0, stamps


async def run_sweep(engine, config: SweepConfig | None = None) -> list[SweepPoint]:
    import random

    config = config or SweepConfig()
    rng = random.Random(config.seed)
    points: list[SweepPoint] = []

    for concurrency in config.concurrencies:
        sem = asyncio.Semaphore(concurrency)
        ttfts: list[float] = []
        itls: list[float] = []
        total_tokens = 0

        async def one():
            nonlocal total_tokens
            tokens = [rng.randrange(10, config.vocab_size) for _ in range(config.isl)]
            async with sem:
                count, ttft, stamps = await _drive_one(engine, tokens, config.osl)
            total_tokens += count
            ttfts.append(ttft)
            itls.extend(b - a for a, b in zip(stamps, stamps[1:]))

        t0 = time.monotonic()
        await asyncio.gather(*[one() for _ in range(config.requests_per_level)])
        wall = time.monotonic() - t0

        ttfts.sort()
        points.append(
            SweepPoint(
                concurrency=concurrency,
                requests=config.requests_per_level,
                wall_s=round(wall, 3),
                output_tokens=total_tokens,
                tok_s_total=round(total_tokens / wall, 2),
                tok_s_per_user=round(total_tokens / wall / concurrency, 2),
                req_s=round(config.requests_per_level / wall, 3),
                ttft_p50_ms=round(ttfts[len(ttfts) // 2] * 1000, 2),
                ttft_p99_ms=round(ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))] * 1000, 2),
                itl_mean_ms=round(sum(itls) / len(itls) * 1000, 3) if itls else 0.0,
            )
        )
    return points


def pareto_frontier(points: list[SweepPoint]) -> list[SweepPoint]:
    """Non-dominated points in (tok_s_per_user, tok_s_total) space."""
    frontier = []
    for p in points:
        dominated = any(
            q.tok_s_per_user >= p.tok_s_per_user and q.tok_s_total > p.tok_s_total
            or q.tok_s_per_user > p.tok_s_per_user and q.tok_s_total >= p.tok_s_total
            for q in points
        )
        if not dominated:
            frontier.append(p)
    return sorted(frontier, key=lambda p: p.tok_s_per_user)


def write_results(points: list[SweepPoint], path) -> None:
    with open(path, "w") as f:
        for p in points:
            f.write(json.dumps(asdict(p)) + "\n")
