"""Synthetic trace generation with shared-prefix structure.

(Reference: benchmarks/data_generator/synthesizer.py — mooncake-style traces
with a prefix tree, hasher/sampler/prefix_analyzer.)  Requests are token-id
sequences drawn from a random prefix tree, so KV-aware routing and prefix
caching see realistic overlap; arrival times follow a Poisson process.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import asdict, dataclass, field
from pathlib import Path


@dataclass
class TraceRequest:
    request_id: int
    arrival_s: float
    token_ids: list[int]
    osl: int

    @property
    def isl(self) -> int:
        return len(self.token_ids)


@dataclass
class SynthesizerConfig:
    num_requests: int = 256
    request_rate: float = 8.0          # Poisson arrivals/s
    vocab_size: int = 32_000
    # prefix tree: depth levels × branching, each node contributing a span
    tree_depth: int = 3
    tree_branching: int = 4
    prefix_span_tokens: int = 64       # tokens contributed per tree level
    unique_suffix_tokens: int = 128    # per-request unique tail (mean)
    osl_mean: int = 128
    seed: int = 0


class TraceSynthesizer:
    def __init__(self, config: SynthesizerConfig):
        self.config = config
        self._rng = random.Random(config.seed)
        # materialize the prefix tree: path -> token span
        self._spans: dict[tuple, list[int]] = {}

    def _span(self, path: tuple) -> list[int]:
        span = self._spans.get(path)
        if span is None:
            rng = random.Random(hash((self.config.seed, path)) & 0xFFFFFFFF)
            span = [rng.randrange(10, self.config.vocab_size) for _ in range(self.config.prefix_span_tokens)]
            self._spans[path] = span
        return span

    def generate(self) -> list[TraceRequest]:
        cfg = self.config
        requests = []
        t = 0.0
        for i in range(cfg.num_requests):
            t += self._rng.expovariate(cfg.request_rate)
            # random path through the tree
            path: tuple = ()
            tokens: list[int] = []
            depth = self._rng.randint(1, cfg.tree_depth)
            for _ in range(depth):
                path = path + (self._rng.randrange(cfg.tree_branching),)
                tokens.extend(self._span(path))
            n_suffix = max(1, int(self._rng.expovariate(1.0 / cfg.unique_suffix_tokens)))
            tokens.extend(
                self._rng.randrange(10, cfg.vocab_size) for _ in range(n_suffix)
            )
            osl = max(1, int(self._rng.expovariate(1.0 / cfg.osl_mean)))
            requests.append(TraceRequest(request_id=i, arrival_s=t, token_ids=tokens, osl=osl))
        return requests

    def write_jsonl(self, path: str | Path) -> list[TraceRequest]:
        requests = self.generate()
        with open(path, "w") as f:
            for r in requests:
                f.write(json.dumps(asdict(r)) + "\n")
        return requests


@dataclass
class SessionTurn:
    arrival_gap_s: float        # gap after the previous turn's last token
    user_tokens: list[int]      # this turn's new user input
    osl: int                    # assistant tokens to generate


@dataclass
class Session:
    session_id: int
    start_s: float
    system_tokens: list[int]    # session prefix (system prompt / doc context)
    turns: list[SessionTurn]


@dataclass
class SessionConfig:
    """Multi-turn chat workload (reference: the KV-routing 3x-TTFT claim is
    demonstrated on multi-turn traffic, docs/architecture/architecture.md:86-91):
    each session's growing history is ITS OWN prefix, so sessions spread load
    across workers while an affine router turns every follow-up turn into a
    tail-only prefill."""

    num_sessions: int = 40
    turns_per_session: int = 5
    session_rate: float = 3.0          # Poisson session starts/s
    system_tokens: int = 768           # per-session shared prefix
    user_tokens_per_turn: int = 64
    turn_gap_mean_s: float = 3.0       # think time between turns
    osl: int = 24
    vocab_size: int = 32_000
    seed: int = 0


def generate_sessions(cfg: SessionConfig) -> list[Session]:
    rng = random.Random(cfg.seed)
    sessions = []
    t = 0.0
    for sid in range(cfg.num_sessions):
        t += rng.expovariate(cfg.session_rate)
        turns = [
            SessionTurn(
                arrival_gap_s=(
                    0.0 if i == 0 else rng.expovariate(1.0 / cfg.turn_gap_mean_s)
                ),
                user_tokens=[
                    rng.randrange(10, cfg.vocab_size)
                    for _ in range(cfg.user_tokens_per_turn)
                ],
                osl=cfg.osl,
            )
            for i in range(cfg.turns_per_session)
        ]
        sessions.append(
            Session(
                session_id=sid,
                start_s=t,
                system_tokens=[
                    rng.randrange(10, cfg.vocab_size)
                    for _ in range(cfg.system_tokens)
                ],
                turns=turns,
            )
        )
    return sessions


def load_trace(path: str | Path) -> list[TraceRequest]:
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                d = json.loads(line)
                out.append(TraceRequest(**d))
    return out


def analyze_prefix_sharing(requests: list[TraceRequest], block_size: int = 16) -> dict:
    """Prefix-overlap statistics (reference: prefix_analyzer) — what fraction
    of request blocks are shared with at least one earlier request."""
    from dynamo_tpu.llm.kv_router.hashing import compute_block_hashes

    seen: set[int] = set()
    total_blocks = 0
    shared_blocks = 0
    for r in requests:
        hashes = compute_block_hashes(r.token_ids, block_size)
        total_blocks += len(hashes)
        for h in hashes:
            if h in seen:
                shared_blocks += 1
            else:
                seen.add(h)
    return {
        "total_blocks": total_blocks,
        "shared_blocks": shared_blocks,
        "sharing_ratio": shared_blocks / total_blocks if total_blocks else 0.0,
        "mean_isl": sum(r.isl for r in requests) / len(requests) if requests else 0,
        "mean_osl": sum(r.osl for r in requests) / len(requests) if requests else 0,
    }
