"""Benchmarking toolkit: trace synthesis, concurrency sweeps, SLA profiling
(reference: benchmarks/ — perf.sh genai-perf sweep, data_generator trace
synthesizer, profiler/profile_sla.py)."""
