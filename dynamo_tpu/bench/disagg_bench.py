"""Disaggregated prefill/decode throughput benchmark (one process).

Produces the disagg analog of the reference's headline number — req/s and
decode-phase tok/s with prefill running on a DIFFERENT engine than decode,
KV shipped via the transfer plane (reference measurement:
examples/llm/benchmarks/README.md:309-319, where decode workers report
tok/s/GPU with prefill disaggregated onto other GPUs).

On one chip both engines share the accelerator, so this is NOT two-chip
disagg — what it measures end-to-end is the full disagg machinery in the
serving path at realistic geometry: router decision, prefill queue, remote
prefill, block-exact KV landing, decode continuation.  The useful outputs
are (a) disagg_req_s / decode-phase tok/s through that path, and (b)
``disagg_overhead_pct`` vs the same workload on a single aggregated
engine — the cost of the disagg plumbing itself, which on real multi-chip
deployments is the part this framework owns (compute overlap is the
hardware's business).

Two further sections exercise this PR's streamed-transfer path:

- ``streamed_ab`` — same disagg stack, chunked prefill engine, streamed
  (DYN_KV_STREAM-style multi-part) vs single-shot transfer: TTFT p50/p99
  per mode, parts shipped, and the transfer-hidden fraction (share of
  transfer wall time overlapped with prefill compute).
- ``fleet`` — a second decode candidate behind an unequal link: requests
  share a prefix held by the "near" (ici) worker while the "far" worker
  sits behind dcn; the KV-locality/link-cost scorer routes each request
  and the section records pick counts + fleet TTFT.

Usage:
    python -m dynamo_tpu.bench.disagg_bench                # auto geometry
    python -m dynamo_tpu.bench.disagg_bench --model tiny   # CPU smoke
Writes DISAGG_BENCH.json (or --out) and prints one JSON line.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time


def _build_engine(model: str, quant: str | None, kv_dtype: str, isl: int,
                  osl: int, batch: int, prefill_only: bool = False,
                  chunk: int | None | str = "auto"):
    import jax
    import numpy as np

    from dynamo_tpu.engine.engine import EngineConfig, JaxLlmEngine
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.models.registry import get_family

    family = get_family("llama")
    if model == "tiny":
        cfg = LlamaConfig.tiny()
    else:
        cfg = getattr(LlamaConfig, model)()
    max_len = isl + osl + 32
    block_size = 16 if model != "tiny" else 4
    num_blocks = batch * ((max_len + block_size - 1) // block_size) + 8

    def shaped(k):
        p = family.init_params(cfg, k)
        if quant:
            from dynamo_tpu.ops.quant import quantize_params

            p = quantize_params(p, family.quant_leaves)
        return p

    shapes = jax.eval_shape(shaped, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda s: np.full(
            s.shape, 1 if np.issubdtype(s.dtype, np.integer) else 0.01,
            dtype=s.dtype,
        ),
        shapes,
    )
    engine = JaxLlmEngine(
        EngineConfig(
            model=cfg,
            num_blocks=num_blocks,
            block_size=block_size,
            max_batch_size=batch,
            max_model_len=max_len,
            # chunked prefill keeps the compile small at ISL 3000 (same
            # rationale as bench.py's accelerator default); callers force
            # ``chunk`` when streamed transfer needs chunks at tiny ISL
            prefill_buckets=(min(512, isl),),
            prefill_chunk_tokens=(
                (min(512, isl) if isl > 512 else None)
                if chunk == "auto" else chunk
            ),
            decode_steps=1 if prefill_only else 8,
            top_logprobs_k=0,
            logit_bias_k=0,
            quantize=quant,
            kv_cache_dtype=kv_dtype,
        ),
        params=params,
    )
    engine.start()
    return engine, cfg


async def run(args: argparse.Namespace) -> dict:
    import jax
    import numpy as np

    from dynamo_tpu.llm.disagg import (
        DisaggConfig,
        DisaggDecodeEngine,
        DisaggRouter,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.llm.protocols.common import (
        Annotated,
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime import Context, DistributedRuntime
    from dynamo_tpu.runtime.controlplane.memory import MemoryControlPlane
    from dynamo_tpu.utils.config import RuntimeConfig

    quant = None if args.quant in (None, "none") else args.quant

    # HBM pre-flight (same rationale as bench.py's DoesNotFit check, which
    # shares this construction recipe — keep the two in sync): don't burn
    # minutes of a live-TPU window initializing engines the chip cannot
    # hold, and don't crash the roundup stage — report a clean skip.
    from dynamo_tpu.engine.engine import resolve_kv_cache_dtype
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.models.registry import get_family

    cfg_pre = (LlamaConfig.tiny() if args.model == "tiny"
               else getattr(LlamaConfig, args.model)())
    family = get_family("llama")

    def tree_bytes(tree):
        return sum(
            int(np.prod(x.shape)) * x.dtype.itemsize
            for x in jax.tree.leaves(tree)
        )

    def shaped(k):
        p = family.init_params(cfg_pre, k)
        if quant:
            from dynamo_tpu.ops.quant import quantize_params

            p = quantize_params(p, family.quant_leaves)
        return p

    param_bytes = tree_bytes(jax.eval_shape(shaped, jax.random.PRNGKey(0)))
    max_len = args.isl + args.osl + 32
    bs = 16 if args.model != "tiny" else 4
    blocks_per_seq = (max_len + bs - 1) // bs
    cache_bytes = tree_bytes(jax.eval_shape(
        lambda: family.cache_init(
            cfg_pre, (args.batch + 2) * blocks_per_seq + 16, bs,
            resolve_kv_cache_dtype(args.kv_dtype),
        )
    ))
    need = 2 * param_bytes + cache_bytes + 2.0e9  # both engines + HLO temps
    try:
        limit = jax.devices()[0].memory_stats().get("bytes_limit")
    except Exception:  # noqa: BLE001 — backends without memory stats
        limit = None
    if limit and need > limit:
        return {
            "skipped": f"{args.model}: 2x params + caches "
                       f"{need/1e9:.1f}GB > HBM {limit/1e9:.1f}GB",
            "model": args.model,
        }

    print(
        f"disagg-bench: building decode + prefill engines "
        f"({args.model}/{quant or 'bf16'})", file=sys.stderr,
    )
    t0 = time.monotonic()
    decode_engine, cfg = _build_engine(
        args.model, quant, args.kv_dtype, args.isl, args.osl, args.batch
    )
    # the PrefillWorker handles one request at a time (its loop awaits each
    # _handle serially), so the prefill engine needs blocks for ~1 sequence
    # — batch-sizing it would waste several GB of the shared chip's HBM
    # chunked even at tiny ISL so the streamed transfer has parts to
    # overlap (chunk = 2 blocks for the tiny smoke; 512 for real models)
    prefill_engine, _ = _build_engine(
        args.model, quant, args.kv_dtype, args.isl, args.osl, batch=2,
        prefill_only=True,
        chunk=min(512, args.isl) if args.isl > 512 else 8,
    )
    print(
        f"disagg-bench: engines up in {time.monotonic()-t0:.1f}s",
        file=sys.stderr,
    )

    MemoryControlPlane.reset_named()
    rt = await DistributedRuntime.create(
        RuntimeConfig(control_plane="memory://disagg-bench")
    )
    rng = np.random.default_rng(0)

    def make_request(tokens: list[int] | None = None) -> dict:
        if tokens is None:
            tokens = rng.integers(10, cfg.vocab_size - 10, size=args.isl).tolist()
        return PreprocessedRequest(
            token_ids=tokens,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=args.osl, ignore_eos=True),
            eos_token_ids=[],
        ).to_wire()

    itls: list[float] = []
    ttfts: list[float] = []
    spans: list[tuple[float, float, int]] = []

    async def drive(gen, req: dict) -> int:
        t0 = time.monotonic()
        ttft = t_last = None
        count = 0
        stream = await gen(Context(req))
        async for item in stream:
            ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
            if ann.data is None or not ann.data.token_ids:
                continue
            t_last = time.monotonic()
            if ttft is None:
                ttft = t_last - t0
                ttfts.append(ttft)
            count += len(ann.data.token_ids)
        if ttft is not None and count > 1:
            itls.append((t_last - t0 - ttft) / (count - 1))
            spans.append((t0 + ttft, t_last, count))
        return count

    def _pctile(xs: list[float], q: float) -> float | None:
        if not xs:
            return None
        s = sorted(xs)
        return s[min(len(s) - 1, round(q * (len(s) - 1)))]

    def ttft_stats() -> dict:
        return {
            "ttft_p50_ms": round(1e3 * _pctile(ttfts, 0.5), 2) if ttfts else None,
            "ttft_p99_ms": round(1e3 * _pctile(ttfts, 0.99), 2) if ttfts else None,
        }

    def phase_stats() -> dict:
        if not spans:
            return {}
        t0g = min(s[0] for s in spans)
        t1g = max(s[1] for s in spans)
        toks = sum(s[2] - 1 for s in spans)
        return {
            "decode_phase_tok_s": (
                round(toks / (t1g - t0g), 2) if t1g > t0g else None
            ),
            "itl_mean_ms": round(1e3 * sum(itls) / len(itls), 2),
        }

    result: dict = {
        "model": args.model,
        "quantize": quant,
        "num_requests": args.requests,
        "isl": args.isl,
        "osl": args.osl,
        "batch": args.batch,
    }
    disagg = prefill_worker = router = None
    disagg2 = decode2 = None
    try:
        # -- aggregated reference: same workload, one engine does both ----
        await drive(decode_engine.generate, make_request())  # warm compiles
        itls.clear(); spans.clear(); ttfts.clear()
        t0 = time.monotonic()
        counts = await asyncio.gather(
            *[drive(decode_engine.generate, make_request())
              for _ in range(args.requests)]
        )
        agg_wall = time.monotonic() - t0
        result["aggregated"] = {
            "wall_s": round(agg_wall, 2),
            "req_s": round(args.requests / agg_wall, 3),
            "tok_s": round(sum(counts) / agg_wall, 2),
            **ttft_stats(),
            **phase_stats(),
        }

        # -- disaggregated: every prefill goes remote ---------------------
        router = DisaggRouter(
            rt, args.model,
            DisaggConfig(max_local_prefill_length=1,
                         max_prefill_queue_size=args.requests + 1),
        )
        queue = PrefillQueue(rt, "bench", "disagg")
        disagg = DisaggDecodeEngine(rt, decode_engine, router, queue)
        await disagg.start()
        prefill_worker = PrefillWorker(rt, prefill_engine, queue)
        prefill_worker.start()

        await drive(disagg.generate, make_request())  # warm prefill engine
        itls.clear(); spans.clear(); ttfts.clear()
        warm_remote = disagg.remote_prefills  # exclude warmup from the count
        t0 = time.monotonic()
        counts = await asyncio.gather(
            *[drive(disagg.generate, make_request())
              for _ in range(args.requests)]
        )
        dis_wall = time.monotonic() - t0
        remote = disagg.remote_prefills - warm_remote
        result["disagg"] = {
            "wall_s": round(dis_wall, 2),
            "req_s": round(args.requests / dis_wall, 3),
            "tok_s": round(sum(counts) / dis_wall, 2),
            # must equal num_requests — a shortfall means a measured request
            # silently fell back to local prefill
            "remote_prefills": remote,
            "all_prefills_remote": remote == args.requests,
            **ttft_stats(),
            **phase_stats(),
        }
        result["disagg_overhead_pct"] = round(
            (dis_wall - agg_wall) / agg_wall * 100, 1
        )

        # -- streamed vs single-shot A/B over the same disagg stack -------
        # (the main disagg section above already ran with the default
        # streaming knob; these two runs pin the worker's mode explicitly)
        ab: dict = {}
        # single-shot first so the worker left running for the fleet section
        # below is the (default-on) streamed one
        for mode_name, mode in (("single_shot", False), ("streamed", True)):
            await prefill_worker.stop()
            prefill_worker = PrefillWorker(
                rt, prefill_engine, queue, stream=mode
            )
            prefill_worker.start()
            base = disagg.stats()
            itls.clear(); spans.clear(); ttfts.clear()
            t0 = time.monotonic()
            await asyncio.gather(
                *[drive(disagg.generate, make_request())
                  for _ in range(args.requests)]
            )
            wall = time.monotonic() - t0
            cur = disagg.stats()
            xfer_s = (cur["disagg_kv_transfer_seconds_total"]
                      - base["disagg_kv_transfer_seconds_total"])
            hidden_s = (cur["disagg_kv_transfer_hidden_seconds_total"]
                        - base["disagg_kv_transfer_hidden_seconds_total"])
            ab[mode_name] = {
                "wall_s": round(wall, 2),
                "kv_parts": (cur["disagg_kv_transfer_parts_total"]
                             - base["disagg_kv_transfer_parts_total"]),
                "transfer_hidden_fraction": (
                    round(hidden_s / xfer_s, 3) if xfer_s > 0 else 0.0
                ),
                **ttft_stats(),
            }
        if ab["streamed"]["ttft_p50_ms"] and ab["single_shot"]["ttft_p50_ms"]:
            ab["ttft_p50_speedup"] = round(
                ab["single_shot"]["ttft_p50_ms"] / ab["streamed"]["ttft_p50_ms"], 3
            )
        result["streamed_ab"] = ab

        # -- routed fleet: 2 decode candidates, unequal overlap + links ---
        # requests share a prefix the "near" candidate already holds; the
        # "far" candidate sits on another slice — the KV-locality/link-cost
        # scorer should send the traffic near.  Link classes are NOT
        # hand-fed (DYN_TRANSFER_HOP stays unset): each worker publishes a
        # TopologyCard and the watcher-discovered map feeds the cost model.
        from dynamo_tpu.llm.kv_router import (
            KvScheduler,
            RadixTree,
            TransferCostModel,
            compute_block_hashes,
        )
        from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, RouterEvent
        from dynamo_tpu.topology import TopologyWatcher, local_card

        decode2, _ = _build_engine(
            args.model, quant, args.kv_dtype, args.isl, args.osl, args.batch
        )
        disagg2 = DisaggDecodeEngine(rt, decode2, router, queue)
        await disagg2.start()
        shared = rng.integers(10, cfg.vocab_size - 10, size=args.isl // 2).tolist()
        tree = RadixTree()
        tree.apply(RouterEvent(
            worker_id=1,
            event=KvCacheEvent(
                kind="stored", block_hashes=compute_block_hashes(shared, bs)
            ),
        ))
        # discovery: the prefill source shares slice s0 with decode worker 1;
        # decode worker 2 reports slice s1, so the map classifies the
        # prefill→2 pair dcn and the scorer prices its transfers accordingly
        for wid, role, slice_label in (
            (17, "prefill", "s0"), (1, "decode", "s0"), (2, "decode", "s1"),
        ):
            card = local_card(wid, role=role, slice_label=slice_label)
            await rt.plane.kv.put(card.key(), card.to_json())
        topo_watch = TopologyWatcher(rt)
        await topo_watch.start()
        for _ in range(200):
            if len(topo_watch.map.nodes) >= 3:
                break
            await asyncio.sleep(0.01)
        cost_model = TransferCostModel()
        cost_model.attach_topology(topo_watch.map)
        sched = KvScheduler()
        fleet_engines = {1: disagg, 2: disagg2}
        picks = {1: 0, 2: 0}
        itls.clear(); spans.clear(); ttfts.clear()

        async def fleet_one() -> None:
            tokens = shared + rng.integers(
                10, cfg.vocab_size - 10, size=args.isl - len(shared)
            ).tolist()
            hashes = compute_block_hashes(tokens, bs)
            overlap = tree.find_matches(hashes)
            missing = {
                w: len(hashes) - overlap.scores.get(w, 0) for w in (1, 2)
            }
            costs = cost_model.costs([1, 2], missing)
            wid, _ratio = sched.select_worker(
                [1, 2], overlap, len(hashes), transfer_costs=costs
            )
            picks[wid] += 1
            await drive(fleet_engines[wid].generate, make_request(tokens))

        t0 = time.monotonic()
        await asyncio.gather(*[fleet_one() for _ in range(args.requests)])
        fleet_wall = time.monotonic() - t0
        result["fleet"] = {
            "decode_workers": 2,
            "topology_discovered": topo_watch.map.informative(),
            "near": {"worker": 1,
                     "hop": topo_watch.map.inbound_hop(1),
                     "bandwidth_bps": topo_watch.map.pair_bandwidth(17, 1),
                     "overlap_blocks": len(compute_block_hashes(shared, bs)),
                     "picks": picks[1]},
            "far": {"worker": 2,
                    "hop": topo_watch.map.inbound_hop(2),
                    "bandwidth_bps": topo_watch.map.pair_bandwidth(17, 2),
                    "overlap_blocks": 0,
                    "picks": picks[2]},
            "preferred_is_near": picks[1] > picks[2],
            "wall_s": round(fleet_wall, 2),
            **ttft_stats(),
        }
        await topo_watch.stop()
        dev = jax.devices()[0]
        result["platform"] = dev.platform
        result["device_kind"] = dev.device_kind
        result["note"] = (
            "single-chip: both engines share the accelerator, so compute "
            "does not overlap; overhead_pct prices the disagg plumbing "
            "(router/queue/KV transfer/landing), not two-chip speedup"
        )
    finally:
        if prefill_worker is not None:
            await prefill_worker.stop()
        if disagg is not None:
            await disagg.stop()
        if disagg2 is not None:
            await disagg2.stop()
        if router is not None:
            await router.stop()
        await rt.close()
        decode_engine.stop()
        prefill_engine.stop()
        if decode2 is not None:
            decode2.stop()
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--model", default=None,
                        help="llama config name or 'tiny' (default: "
                        "llama32_3b on TPU, tiny elsewhere)")
    parser.add_argument("--quant", default=None,
                        help="int8 or none (default: int8 for real models)")
    parser.add_argument("--kv-dtype", default="bf16")
    parser.add_argument("--isl", type=int, default=None)
    parser.add_argument("--osl", type=int, default=None)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--out", default="DISAGG_BENCH.json")
    args = parser.parse_args()

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    if args.model is None:
        args.model = "llama32_3b" if on_tpu else "tiny"
    if args.quant is None:
        args.quant = "int8" if args.model.startswith("llama3") else "none"
    if args.isl is None:
        args.isl = 3000 if args.model != "tiny" else 24
    if args.osl is None:
        args.osl = 150 if args.model != "tiny" else 8
    if args.model == "tiny":
        args.batch = min(args.batch, 4)
        args.requests = min(args.requests, 6)

    result = asyncio.run(run(args))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
