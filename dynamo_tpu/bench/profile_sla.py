"""SLA profiler: measure prefill/decode performance over an (isl, osl) grid
to produce the planner's PerfProfile (reference:
benchmarks/profiler/profile_sla.py feeding the SLA planner's interpolators).
"""

from __future__ import annotations

import asyncio
import math
import random
import time

from dynamo_tpu.bench.sweep import _drive_one
from dynamo_tpu.planner.perf_interpolation import PerfProfile, ProfilePoint


async def _profile_point(
    engine, isl: int, osl: int, concurrency: int, requests: int, vocab_size: int,
    rng: random.Random,
) -> ProfilePoint:
    ttfts, itls, prefill_rates = [], [], []
    total_tokens = 0
    t0 = time.monotonic()
    sem = asyncio.Semaphore(concurrency)
    # draw every request's tokens up front: with the semaphore, draw order
    # inside the tasks would depend on completion timing and break the
    # seed's run-to-run reproducibility
    prompts = [
        [rng.randrange(10, vocab_size) for _ in range(isl)] for _ in range(requests)
    ]

    async def one(tokens: list[int]) -> None:
        nonlocal total_tokens
        async with sem:
            count, ttft, stamps = await _drive_one(engine, tokens, osl)
            total_tokens += count
            if ttft > 0:
                ttfts.append(ttft)
                prefill_rates.append(isl / ttft)
            itls.extend(b - a for a, b in zip(stamps, stamps[1:]))

    # closed-loop load HELD at the target concurrency: a finished request's
    # slot is immediately refilled (batching into gather waves would decay
    # to concurrency 1 as stragglers finish; same pattern as sweep.py)
    await asyncio.gather(*[one(tokens) for tokens in prompts])
    wall = time.monotonic() - t0
    return ProfilePoint(
        isl=isl,
        osl=osl,
        concurrency=concurrency,
        prefill_tok_s=sum(prefill_rates) / len(prefill_rates) if prefill_rates else 0.0,
        decode_tok_s=total_tokens / wall,
        ttft_s=sum(ttfts) / len(ttfts) if ttfts else 0.0,
        itl_s=sum(itls) / len(itls) if itls else 0.0,
    )


async def profile_engine(
    engine,
    *,
    isl_grid=(128, 512, 2048),
    osl_grid=(32, 128),
    concurrency_grid=(1,),
    requests_per_point: int = 4,
    vocab_size: int = 32_000,
    seed: int = 0,
) -> PerfProfile:
    rng = random.Random(seed)
    points: list[ProfilePoint] = []
    for isl in isl_grid:
        for osl in osl_grid:
            for conc in concurrency_grid:
                points.append(
                    await _profile_point(
                        engine, isl, osl, conc,
                        max(requests_per_point, conc), vocab_size, rng,
                    )
                )
    return PerfProfile(points)


def plan_deployment(
    profile: PerfProfile,
    *,
    isl: int,
    osl: int,
    target_rps: float,
    ttft_sla_s: float,
    itl_sla_s: float,
) -> dict:
    """SLA planner (reference: benchmarks/profiler feeding the SLA planner):
    pick the highest profiled concurrency whose measured TTFT and ITL still
    meet the SLAs at this workload shape, derive per-worker request
    throughput from it, and size the worker fleet for the target load.

    Returns ``{status, concurrency, per_worker_rps, replicas, ttft_s,
    itl_s}``.  ``status`` distinguishes the two empty cases: "infeasible"
    (the shape WAS profiled but no concurrency meets the SLAs — scale the
    model or the slice) vs a ValueError for a shape that was never profiled
    (re-profile at the real workload shape before planning).
    """
    shape_points = [p for p in profile.points if p.isl == isl and p.osl == osl]
    if not shape_points:
        profiled = sorted({(p.isl, p.osl) for p in profile.points})
        raise ValueError(
            f"shape (isl={isl}, osl={osl}) was never profiled "
            f"(profiled shapes: {profiled}); re-run profile_engine on it"
        )
    candidates = [
        p for p in shape_points
        # decode_tok_s > 0 also excludes dead points whose zero-sentinel
        # latencies would trivially "meet" any SLA
        if p.decode_tok_s > 0 and p.ttft_s <= ttft_sla_s and p.itl_s <= itl_sla_s
    ]
    if not candidates:
        return {"status": "infeasible", "concurrency": 0, "per_worker_rps": 0.0,
                "replicas": 0, "ttft_s": None, "itl_s": None}
    best = max(candidates, key=lambda p: p.decode_tok_s)
    per_worker_rps = best.decode_tok_s / max(osl, 1)
    replicas = max(1, math.ceil(target_rps / per_worker_rps)) if target_rps > 0 else 1
    return {
        "status": "ok",
        "concurrency": best.concurrency,
        "per_worker_rps": per_worker_rps,
        "replicas": replicas,
        "ttft_s": best.ttft_s,
        "itl_s": best.itl_s,
    }
