"""SLA profiler: measure prefill/decode performance over an (isl, osl) grid
to produce the planner's PerfProfile (reference:
benchmarks/profiler/profile_sla.py feeding the SLA planner's interpolators).
"""

from __future__ import annotations

import asyncio
import random
import time

from dynamo_tpu.bench.sweep import _drive_one
from dynamo_tpu.planner.perf_interpolation import PerfProfile, ProfilePoint


async def profile_engine(
    engine,
    *,
    isl_grid=(128, 512, 2048),
    osl_grid=(32, 128),
    requests_per_point: int = 4,
    vocab_size: int = 32_000,
    seed: int = 0,
) -> PerfProfile:
    rng = random.Random(seed)
    points: list[ProfilePoint] = []
    for isl in isl_grid:
        for osl in osl_grid:
            ttfts, itls, prefill_rates = [], [], []
            total_tokens = 0
            t0 = time.monotonic()
            for _ in range(requests_per_point):
                tokens = [rng.randrange(10, vocab_size) for _ in range(isl)]
                count, ttft, stamps = await _drive_one(engine, tokens, osl)
                total_tokens += count
                if ttft > 0:
                    ttfts.append(ttft)
                    prefill_rates.append(isl / ttft)
                itls.extend(b - a for a, b in zip(stamps, stamps[1:]))
            wall = time.monotonic() - t0
            points.append(
                ProfilePoint(
                    isl=isl,
                    osl=osl,
                    concurrency=1,
                    prefill_tok_s=sum(prefill_rates) / len(prefill_rates) if prefill_rates else 0.0,
                    decode_tok_s=total_tokens / wall,
                    ttft_s=sum(ttfts) / len(ttfts) if ttfts else 0.0,
                    itl_s=sum(itls) / len(itls) if itls else 0.0,
                )
            )
    return PerfProfile(points)
