"""Routed-fleet TTFT benchmark: KV-aware routing vs random routing.

The reference's headline routing claim is ~3x TTFT from KV-aware routing on
multi-turn traffic (reference: docs/architecture/architecture.md:86-91);
this module measures the same effect end-to-end through THIS repo's real
stack: N mocker workers (real BlockAllocator + Scheduler, reference cost
model) served on control-plane endpoints with real KV-event/load publishers,
a real KvRouter radix index fed from the bus, and dispatch through
PushRouter — the only simulated part is the device compute.

Workload: multi-turn sessions (bench.data_generator.generate_sessions).
Each session's growing history is its own prefix: sessions spread load
across the fleet, while an affine (KV-aware) router turns every follow-up
turn into a tail-only prefill.  Turn prompts embed the ACTUAL streamed
assistant tokens, exactly like a chat client echoing history.  Both
policies replay the same sessions against a fresh fleet; TTFT includes
queueing.  Times are simulation-compressed (speedup-scaled) wall seconds,
so absolute numbers are synthetic but the kv/random RATIO is scale-free —
the ratio is the result.

Run: ``python -m dynamo_tpu.bench.routed_fleet [--out ROUTED_FLEET.json]``
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, replace

from dynamo_tpu.bench.data_generator import Session, SessionConfig, generate_sessions
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.llm.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.llm.mocker import MockerConfig, MockerEngine
from dynamo_tpu.llm.protocols.common import (
    Annotated,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.runtime.client import PushRouter, RouterMode
from dynamo_tpu.runtime.controlplane.memory import MemoryControlPlane
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("bench.routed_fleet")


@dataclass
class FleetConfig:
    num_workers: int = 4
    block_size: int = 16
    num_blocks: int = 2048
    max_batch_size: int = 16
    # modest compression: at high speedups the wall-clock dispatch overhead
    # (TCP rendezvous, event loop) drowns the compressed compute deltas and
    # the measurement stops being about routing at all
    speedup: float = 10.0
    # load metrics cadence in SIMULATED seconds (production publishes at
    # ~1s against real traffic; a cadence much slower than the per-turn
    # service time leaves the router's load view stale and lets affine
    # traffic herd onto busy workers)
    metrics_period_sim_s: float = 0.25
    # "mocker" (reference-style cost-model sim — how the reference validates
    # routing, lib/llm/src/mocker/) or "jax": REAL JaxLlmEngine workers
    # whose TTFT deltas come from actual prefill compute saved by prefix
    # caching.  jax mode requires speedup=1.0 — service time is real, so
    # compressed arrivals would measure queue saturation, not routing.
    engine: str = "mocker"
    # jax mode: model config (None = LlamaConfig.tiny, the CPU geometry);
    # on TPU pass a real model for the on-device routing artifact
    model_config: object = None
    # jax mode: engine context window; size it to the workload's longest
    # history (main() computes this from the session config)
    max_model_len: int = 512
    # parked-session mode (run_parked): host offload tier size in blocks —
    # 0 mounts no tier (the plain routing bench); the prefetch gate for the
    # engines (None = DYN_PREFETCH env); and an emulated per-block page-in
    # latency applied to EVERY tier read (demand and prefetch alike, so the
    # comparison is fair) — on this CPU container host-tier reads are
    # page-cache-fast, while production disk/DCN tiers pay real IO, and the
    # bench's point is WHERE that latency lands, not how big it is
    host_offload_blocks: int = 0
    prefetch: bool | None = None
    page_delay_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.engine == "jax" and self.speedup != 1.0:
            raise ValueError(
                "engine='jax' requires speedup=1.0: real engines serve in "
                "real time, so compressed arrivals measure queue depth "
                "instead of routing"
            )


def _make_fleet_engine(cfg: FleetConfig, params_cache: dict):
    if cfg.engine == "mocker":
        return MockerEngine(
            MockerConfig(
                num_blocks=cfg.num_blocks,
                block_size=cfg.block_size,
                max_batch_size=cfg.max_batch_size,
                speedup=cfg.speedup,
            )
        )
    if cfg.engine == "jax":
        import jax as _jax

        from dynamo_tpu.engine import EngineConfig, JaxLlmEngine
        from dynamo_tpu.models.llama import LlamaConfig, init_params

        mcfg = cfg.model_config or LlamaConfig.tiny()
        if "params" not in params_cache:
            # one host init shared by every worker: engines never mutate
            # params, and N tiny random inits would dominate bring-up
            params_cache["params"] = init_params(mcfg, _jax.random.PRNGKey(0))
        # bucket ladder sized to the context window: every serving program
        # is warmed BEFORE the measured replay (run_fleet), so fewer
        # buckets = faster bring-up, and the top bucket covers max_model_len
        buckets = tuple(
            b for b in (128, 256, 512, 1024, 2048) if b < cfg.max_model_len
        ) + (cfg.max_model_len,)
        engine = JaxLlmEngine(
            EngineConfig(
                model=mcfg,
                num_blocks=cfg.num_blocks,
                block_size=cfg.block_size,
                max_batch_size=cfg.max_batch_size,
                prefill_buckets=buckets,
                max_model_len=cfg.max_model_len,
                host_offload_blocks=cfg.host_offload_blocks,
                prefetch=cfg.prefetch,
            ),
            params=params_cache["params"],
        )
        if cfg.page_delay_ms and engine.host_tier is not None:
            _emulate_tier_latency(engine.host_tier, cfg.page_delay_ms)
        return engine
    raise ValueError(f"unknown fleet engine {cfg.engine!r} (want mocker|jax)")


async def _serve_fleet(rt: DistributedRuntime, cfg: FleetConfig):
    comp = rt.namespace("fleet").component("backend")
    ep = comp.endpoint("generate")
    handles = []
    params_cache: dict = {}
    for _ in range(cfg.num_workers):
        engine = _make_fleet_engine(cfg, params_cache)
        service = await ep.serve(engine, stats_handler=engine.stats)
        kv_pub = KvEventPublisher(comp, worker_id=service.instance.instance_id)
        kv_pub.start()
        # sink attached before the engine loop starts (serve.py invariant):
        # no early request's stored-block events may be dropped
        engine._event_sink = kv_pub.sink
        # jax mode forces speedup=1.0 (FleetConfig.__post_init__), so this
        # division is the identity there and sim-compression for the mocker
        metrics_pub = WorkerMetricsPublisher(
            comp, service.instance.instance_id, engine.stats,
            period_s=cfg.metrics_period_sim_s / cfg.speedup,
        )
        metrics_pub.start()
        engine.start()
        handles.append((engine, service, kv_pub, metrics_pub))
    return comp, ep, handles


async def _teardown_fleet(handles) -> None:
    for engine, service, kv_pub, metrics_pub in handles:
        await metrics_pub.stop()
        await kv_pub.stop()
        await service.shutdown(drain_timeout=1)
        engine.stop()


def _pctile(xs: list[float], q: float) -> float | None:
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * q))] if xs else None


def _emulate_tier_latency(host_tier, page_delay_ms: float) -> None:
    """Give the offload tier a per-block read latency (sleep on the device
    thread, exactly where real disk/DCN IO would block).  Applies to every
    restore — demand paging pays it inside admission, prefetch pays it
    between steps before the request arrives — so only the PLACEMENT of
    the latency differs between the bench's modes."""
    orig = host_tier.read_pinned_many
    delay_s = page_delay_ms / 1000.0

    def slow_read(seq_hashes, _orig=orig, _d=delay_s):
        time.sleep(_d * len(seq_hashes))
        return _orig(seq_hashes)

    host_tier.read_pinned_many = slow_read


async def run_fleet(
    policy: str,
    sessions: list[Session],
    fleet_cfg: FleetConfig | None = None,
    *,
    control_plane: str | None = None,
) -> dict:
    """Replay multi-turn ``sessions`` against a fresh mocker fleet under
    ``policy`` ("kv" or "random"); returns TTFT percentiles (all turns and
    follow-up turns separately) and fleet counters."""
    assert policy in ("kv", "random"), policy
    cfg = fleet_cfg or FleetConfig()
    MemoryControlPlane.reset_named()
    rt = await DistributedRuntime.create(
        RuntimeConfig(control_plane=control_plane or f"memory://fleet-{policy}")
    )
    kv_router = None
    handles = []
    try:
        comp, ep, handles = await _serve_fleet(rt, cfg)
        push = await PushRouter.from_endpoint(ep, mode=RouterMode.RANDOM)
        if policy == "kv":
            kv_router = KvRouter(comp, block_size=cfg.block_size)
            await kv_router.start()
            dispatcher = KvPushRouter(push, kv_router)
        else:
            dispatcher = push
        await push.client.wait_for_instances(cfg.num_workers, timeout=10)
        if cfg.engine == "jax":
            # compile every serving program before the clock starts: lazy
            # compiles inside the replay would dominate first-turn TTFT and
            # drown the routing signal entirely
            for engine, *_ in handles:
                await engine.warmup()

        t_start = time.monotonic()
        first_ttfts: list[float] = []    # turn 0: cold for both policies
        follow_ttfts: list[float] = []   # turns 1+: where affinity matters

        async def one_session(sess: Session) -> None:
            delay = sess.start_s / cfg.speedup - (time.monotonic() - t_start)
            if delay > 0:
                await asyncio.sleep(delay)
            history = list(sess.system_tokens)
            for i, turn in enumerate(sess.turns):
                if turn.arrival_gap_s:
                    await asyncio.sleep(turn.arrival_gap_s / cfg.speedup)
                history.extend(turn.user_tokens)
                wire = PreprocessedRequest(
                    token_ids=list(history),
                    stop=StopConditions(max_tokens=turn.osl, ignore_eos=True),
                    eos_token_ids=[],
                ).to_wire()
                t0 = time.monotonic()
                stream = await dispatcher.generate(Context(wire))
                ttft = None
                async for item in stream:
                    ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
                    if ann.data is None:
                        continue
                    if ann.data.token_ids:
                        if ttft is None:
                            ttft = time.monotonic() - t0
                            (first_ttfts if i == 0 else follow_ttfts).append(ttft)
                        # chat clients echo history: the next turn's prompt
                        # embeds the ACTUAL assistant tokens so the cached
                        # blocks match exactly
                        history.extend(ann.data.token_ids)

        await asyncio.gather(*[one_session(s) for s in sessions])
        wall = time.monotonic() - t_start

        all_ttfts = first_ttfts + follow_ttfts
        # both engine kinds expose the same allocator counter (the mocker
        # reuses the REAL BlockAllocator)
        prefix_hits = sum(h[0].allocator.prefix_hits_total for h in handles)
        ms = lambda x: None if x is None else round(x * 1000, 2)  # noqa: E731
        return {
            "policy": policy,
            "engine": cfg.engine,
            "num_workers": cfg.num_workers,
            "num_sessions": len(sessions),
            "num_turns": len(all_ttfts),
            "wall_s": round(wall, 3),
            # simulation-compressed milliseconds; ratios are scale-free
            "ttft_p50_ms": ms(_pctile(all_ttfts, 0.5)),
            "ttft_p99_ms": ms(_pctile(all_ttfts, 0.99)),
            "ttft_mean_ms": ms(sum(all_ttfts) / len(all_ttfts)),
            "followup_ttft_p50_ms": ms(_pctile(follow_ttfts, 0.5)),
            "followup_ttft_p99_ms": ms(_pctile(follow_ttfts, 0.99)),
            "prefix_hits_total": prefix_hits,
        }
    finally:
        if kv_router is not None:
            await kv_router.stop()
        await _teardown_fleet(handles)
        await rt.close()


async def compare_policies(
    session_cfg: SessionConfig | None = None,
    fleet_cfg: FleetConfig | None = None,
) -> dict:
    """The artifact: same sessions, both policies, headline speedup ratios."""
    session_cfg = session_cfg or SessionConfig()
    fleet_cfg = fleet_cfg or FleetConfig()
    sessions = generate_sessions(session_cfg)
    random_result = await run_fleet("random", sessions, fleet_cfg)
    kv_result = await run_fleet("kv", sessions, fleet_cfg)
    ratio = lambda k: round(random_result[k] / kv_result[k], 2)  # noqa: E731
    out = {
        "workload": {
            "num_sessions": session_cfg.num_sessions,
            "turns_per_session": session_cfg.turns_per_session,
            "system_tokens": session_cfg.system_tokens,
            "user_tokens_per_turn": session_cfg.user_tokens_per_turn,
            "osl": session_cfg.osl,
        },
        "random": random_result,
        "kv": kv_result,
        "ttft_p50_speedup": ratio("ttft_p50_ms"),
        "ttft_p99_speedup": ratio("ttft_p99_ms"),
        "ttft_mean_speedup": ratio("ttft_mean_ms"),
        "followup_ttft_p50_speedup": ratio("followup_ttft_p50_ms"),
    }
    logger.info(
        "kv-routing TTFT speedup: p50 %.2fx p99 %.2fx follow-up-p50 %.2fx",
        out["ttft_p50_speedup"], out["ttft_p99_speedup"],
        out["followup_ttft_p50_speedup"],
    )
    return out


# ---------------------------------------------------------------------------
# Parked-session mode: predictive prefetch vs demand paging vs warm cache
# ---------------------------------------------------------------------------

PARKED_MODES = ("demand", "prefetch", "warm")


def parked_blocks_per_session(session_cfg: SessionConfig, block_size: int) -> int:
    """KV blocks one two-turn session holds after its returning turn —
    sizes the host tier and validates that the workload overflows HBM."""
    tokens = session_cfg.system_tokens + 2 * (
        session_cfg.user_tokens_per_turn + session_cfg.osl
    )
    return tokens // block_size + 2


async def run_parked(
    mode: str,
    sessions: list[Session],
    fleet_cfg: FleetConfig,
    *,
    hint_lead_s: float = 0.4,
    wave: int = 4,
) -> dict:
    """Park ``sessions`` (turn 1 runs, then the session goes idle and its KV
    pages out under HBM pressure), then bring every session back for turn 2
    and measure the RETURNING turn's TTFT.

    - ``demand``:   DYN_PREFETCH=0 semantics — the page-in runs inside
      admission, on the returning request's critical path.
    - ``prefetch``: an arrival hint fires ``hint_lead_s`` before the
      request (the frontend's admission-time hint), the router's forwarder
      targets the worker holding the prefix, and its pager pre-restores the
      blocks — the same page-in, off the critical path.
    - ``warm``:     reference ceiling — HBM sized to hold every session, so
      the returning turn is a pure device prefix hit (caller passes a big
      ``num_blocks``).

    Requires ``engine='jax'`` (the mocker has no KV content to offload)."""
    assert mode in PARKED_MODES, mode
    if fleet_cfg.engine != "jax":
        raise ValueError("parked-session mode needs engine='jax' (real KV)")
    from dynamo_tpu.llm.kv_router.hashing import compute_block_hashes
    from dynamo_tpu.prefetch.hints import PREFETCH_HINT_SUBJECT, PrefetchHint
    from dynamo_tpu.prefetch.worker import PrefetchListener

    MemoryControlPlane.reset_named()
    rt = await DistributedRuntime.create(
        RuntimeConfig(control_plane=f"memory://park-{mode}")
    )
    kv_router = None
    handles = []
    listeners: list[PrefetchListener] = []
    try:
        comp, ep, handles = await _serve_fleet(rt, fleet_cfg)
        push = await PushRouter.from_endpoint(ep, mode=RouterMode.RANDOM)
        # KV-affine dispatch in every mode: the returning turn must land on
        # the worker holding the parked prefix for ANY policy to page it in
        kv_router = KvRouter(
            comp, block_size=fleet_cfg.block_size,
            enable_prefetch=(mode == "prefetch"),
        )
        await kv_router.start()
        dispatcher = KvPushRouter(push, kv_router)
        await push.client.wait_for_instances(fleet_cfg.num_workers, timeout=10)
        for engine, service, *_ in handles:
            if mode == "prefetch":
                assert engine.prefetch_pager is not None, (
                    "prefetch mode needs engines with prefetch enabled"
                )
                listener = PrefetchListener(
                    comp, engine, service.instance.instance_id
                )
                listener.start()
                listeners.append(listener)
            else:
                assert engine.prefetch_pager is None, (
                    f"{mode} mode must run fully demand-driven"
                )
            await engine.warmup()

        async def one_turn(history: list[int], osl: int) -> float:
            """Send one request; returns TTFT and extends history with the
            ACTUAL streamed tokens (chat clients echo history)."""
            wire = PreprocessedRequest(
                token_ids=list(history),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
                eos_token_ids=[],
            ).to_wire()
            t0 = time.monotonic()
            stream = await dispatcher.generate(Context(wire))
            ttft = None
            async for item in stream:
                ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
                if ann.data is None:
                    continue
                if ann.data.token_ids:
                    if ttft is None:
                        ttft = time.monotonic() - t0
                    history.extend(ann.data.token_ids)
            assert ttft is not None
            return ttft

        # -- park: turn 1 for every session, bounded concurrency so later
        # sessions steadily evict earlier ones' blocks to the offload tier
        histories: dict[int, list[int]] = {}
        park_sem = asyncio.Semaphore(wave)

        async def park_one(sess: Session) -> None:
            history = list(sess.system_tokens) + list(sess.turns[0].user_tokens)
            async with park_sem:
                await one_turn(history, sess.turns[0].osl)
            histories[sess.session_id] = history

        await asyncio.gather(*[park_one(s) for s in sessions])
        # let in-flight evictions offload before the fleet goes idle
        await asyncio.sleep(0.2)

        # -- return: turn 2 in waves, oldest (most-evicted) sessions first
        hint_subject = comp.event_subject(PREFETCH_HINT_SUBJECT)
        return_ttfts: list[float] = []

        async def return_one(sess: Session) -> None:
            history = histories[sess.session_id]
            history.extend(sess.turns[1].user_tokens)
            return_ttfts.append(await one_turn(history, sess.turns[1].osl))

        ordered = sorted(sessions, key=lambda s: s.session_id)
        for start in range(0, len(ordered), wave):
            group = ordered[start : start + wave]
            if mode == "prefetch":
                # the admission-time arrival hint, hint_lead_s of paging
                # window ahead of dispatch (frontend → forwarder → worker)
                for sess in group:
                    await rt.plane.bus.publish(
                        hint_subject,
                        PrefetchHint(
                            block_hashes=compute_block_hashes(
                                histories[sess.session_id],
                                fleet_cfg.block_size,
                            )
                        ).to_json(),
                    )
                await asyncio.sleep(hint_lead_s)
            await asyncio.gather(*[return_one(s) for s in group])

        stat_sum = lambda key: sum(  # noqa: E731
            h[0].stats().get(key, 0) for h in handles
        )
        ms = lambda x: None if x is None else round(x * 1000, 2)  # noqa: E731
        return {
            "mode": mode,
            "num_workers": fleet_cfg.num_workers,
            "num_sessions": len(sessions),
            "hbm_blocks_per_worker": fleet_cfg.num_blocks,
            "host_blocks_per_worker": fleet_cfg.host_offload_blocks,
            "emulated_page_delay_ms_per_block": fleet_cfg.page_delay_ms,
            "returning_ttft_p50_ms": ms(_pctile(return_ttfts, 0.5)),
            "returning_ttft_p99_ms": ms(_pctile(return_ttfts, 0.99)),
            "returning_ttft_mean_ms": ms(
                sum(return_ttfts) / len(return_ttfts)
            ),
            "prefix_hits_total": stat_sum("prefix_hits_total"),
            "host_restores_total": stat_sum("host_restores_total"),
            "preemptions_total": stat_sum("num_preemptions_total"),
            "prefetch_hits_total": stat_sum("prefetch_hits_total"),
            "prefetch_misses_total": stat_sum("prefetch_misses_total"),
            "prefetch_blocks_restored_total": stat_sum(
                "prefetch_blocks_restored_total"
            ),
            "prefetch_hidden_seconds_total": round(
                stat_sum("prefetch_hidden_seconds_total"), 4
            ),
        }
    finally:
        for listener in listeners:
            await listener.stop()
        if kv_router is not None:
            await kv_router.stop()
        await _teardown_fleet(handles)
        await rt.close()


async def compare_parked(
    session_cfg: SessionConfig,
    fleet_cfg: FleetConfig,
    *,
    hint_lead_s: float = 0.4,
    wave: int = 4,
) -> dict:
    """The PREFETCH_BENCH artifact: same parked sessions replayed under
    demand paging, predictive prefetch, and a warm-cache ceiling."""
    sessions = generate_sessions(session_cfg)
    parked_blocks = len(sessions) * parked_blocks_per_session(
        session_cfg, fleet_cfg.block_size
    )
    if parked_blocks <= fleet_cfg.num_blocks * fleet_cfg.num_workers:
        raise ValueError(
            f"workload must overflow HBM: {parked_blocks} session blocks vs "
            f"{fleet_cfg.num_blocks * fleet_cfg.num_workers} fleet HBM blocks"
        )
    results = {}
    for mode in PARKED_MODES:
        cfg = replace(
            fleet_cfg,
            prefetch=(mode == "prefetch"),
            # warm ceiling: HBM holds the whole workload, nothing pages
            **(
                dict(
                    num_blocks=parked_blocks + 32 * fleet_cfg.max_batch_size,
                    host_offload_blocks=0,
                    page_delay_ms=0.0,
                )
                if mode == "warm"
                else {}
            ),
        )
        results[mode] = await run_parked(
            mode, sessions, cfg, hint_lead_s=hint_lead_s, wave=wave
        )
    ratio = lambda a, b, k: (  # noqa: E731
        None if not results[b][k] else round(results[a][k] / results[b][k], 2)
    )
    out = {
        "workload": {
            "num_sessions": session_cfg.num_sessions,
            "system_tokens": session_cfg.system_tokens,
            "user_tokens_per_turn": session_cfg.user_tokens_per_turn,
            "osl": session_cfg.osl,
            "parked_blocks": parked_blocks,
            "fleet_hbm_blocks": fleet_cfg.num_blocks * fleet_cfg.num_workers,
            "hint_lead_s": hint_lead_s,
        },
        **results,
        # the headline: how much returning-turn latency prefetch removes
        # vs demand paging, and how close it gets to the warm ceiling
        "demand_over_prefetch_ttft_p50": ratio(
            "demand", "prefetch", "returning_ttft_p50_ms"
        ),
        "demand_over_prefetch_ttft_mean": ratio(
            "demand", "prefetch", "returning_ttft_mean_ms"
        ),
        "prefetch_over_warm_ttft_p50": ratio(
            "prefetch", "warm", "returning_ttft_p50_ms"
        ),
    }
    logger.info(
        "parked-session returning-turn TTFT: demand/prefetch p50 %sx, "
        "prefetch/warm p50 %sx",
        out["demand_over_prefetch_ttft_p50"],
        out["prefetch_over_warm_ttft_p50"],
    )
    return out


def main() -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None)
    parser.add_argument("--num-workers", type=int, default=4)
    parser.add_argument(
        "--sessions", "--num-sessions", dest="num_sessions", type=int,
        default=32,
    )
    parser.add_argument("--turns", type=int, default=4)
    parser.add_argument(
        "--engine", default="mocker", choices=["mocker", "jax"],
        help="mocker = cost-model sim (reference-style); jax = real engines"
    )
    parser.add_argument(
        "--park", action="store_true",
        help="parked-session prefetch bench: sessions >> HBM capacity, "
        "returning-turn TTFT under demand paging vs predictive prefetch vs "
        "a warm-cache ceiling (forces --engine jax, 2 turns)",
    )
    parser.add_argument(
        "--hbm-blocks", type=int, default=96,
        help="park mode: per-worker HBM blocks (the capacity sessions "
        "must overflow)",
    )
    parser.add_argument(
        "--page-delay-ms", type=float, default=2.0,
        help="park mode: emulated per-block tier read latency (0 = raw "
        "host-DRAM speed)",
    )
    parser.add_argument("--hint-lead", type=float, default=0.4)
    args = parser.parse_args()
    if args.park:
        args.engine = "jax"
    if args.out is None:
        args.out = (
            "PREFETCH_BENCH.json" if args.park
            else "ROUTED_FLEET.json" if args.engine == "mocker"
            else "ROUTED_FLEET_JAX.json"
        )
    session_cfg = replace(
        SessionConfig(), num_sessions=args.num_sessions,
        turns_per_session=2 if args.park else args.turns,
        # real engines prefill the real history: keep the workload inside
        # the tiny geometry's bucket ladder (mocker scales are unaffected)
        **(
            dict(system_tokens=160, user_tokens_per_turn=32, osl=8,
                 vocab_size=480)
            if args.park
            else dict(system_tokens=256, user_tokens_per_turn=48, osl=16,
                      vocab_size=480)
            if args.engine == "jax" else {}
        ),
    )
    # jax mode: real-time arrivals (FleetConfig enforces it) and a context
    # window sized to the longest session history so any --turns fits
    extra = {}
    if args.engine == "jax":
        turns = 2 if args.park else args.turns
        longest = (
            session_cfg.system_tokens
            + turns * (session_cfg.user_tokens_per_turn + session_cfg.osl)
            + 32
        )
        extra = {
            "speedup": 1.0,
            "max_model_len": (longest + 127) // 128 * 128,
        }
    fleet_cfg = FleetConfig(
        num_workers=args.num_workers, engine=args.engine, **extra,
    )
    if args.park:
        blocks_per_session = parked_blocks_per_session(
            session_cfg, fleet_cfg.block_size
        )
        fleet_cfg = replace(
            fleet_cfg,
            num_blocks=args.hbm_blocks,
            # the host tier parks the whole fleet's overflow
            host_offload_blocks=args.num_sessions * blocks_per_session + 64,
            page_delay_ms=args.page_delay_ms,
        )
        result = asyncio.run(
            compare_parked(session_cfg, fleet_cfg, hint_lead_s=args.hint_lead)
        )
    else:
        result = asyncio.run(compare_policies(session_cfg, fleet_cfg))
    if args.engine == "jax":
        # stamp where the real engines actually ran — a CPU-fallback
        # artifact must not read as an on-TPU result
        import jax

        dev = jax.devices()[0]
        result["platform"] = dev.platform
        result["device_kind"] = dev.device_kind
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
