"""Fault injection + self-healing runtime primitives.

Two halves (see docs/robustness.md for the failure-mode matrix):

- :mod:`faults`    — a registry of named fault points checked at the
  runtime's seams, armed via ``DYN_FAULTS`` with deterministic triggers so
  chaos scenarios run as ordinary pytest.
- healing building blocks — :mod:`retry` (capped exponential backoff with
  jitter), :mod:`admission` (frontend load shedding), and :mod:`counters`
  (process-global recovery counters exported on every Prometheus surface).

The control-plane reconnect/resync machinery itself lives with the client
(``runtime/controlplane/client.py``) and the safe-retry dispatch policy
with the push router (``runtime/client.py``); both are built from, and
observable through, this package.
"""

from dynamo_tpu.robustness import counters
from dynamo_tpu.robustness.admission import (
    AdmissionConfig,
    AdmissionController,
    Overloaded,
)
from dynamo_tpu.robustness.faults import FAULTS, FaultRegistry, get_faults
from dynamo_tpu.robustness.retry import Backoff

__all__ = [
    "FAULTS",
    "AdmissionConfig",
    "AdmissionController",
    "Backoff",
    "FaultRegistry",
    "Overloaded",
    "counters",
    "get_faults",
]
