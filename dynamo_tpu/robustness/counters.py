"""Process-global resilience counters.

The self-healing paths live in layers that must not depend on
prometheus_client (runtime/controlplane, runtime/client run inside workers,
frontends, and bare tools alike), so recovery events are counted here in a
plain thread-safe dict.  Surfaces that already speak Prometheus pull from
it: the HTTP frontend appends :func:`render` to its ``/metrics`` body and
``components/metrics_service.py`` mirrors the snapshot into gauges.

Known families (always rendered, zero-valued until the first event):

- ``dyn_cp_reconnects_total`` — control-plane connections re-established
- ``dyn_retries_total``       — requests re-dispatched pre-first-token
- ``dyn_shed_total``          — requests shed by frontend admission control
- ``dyn_faults_injected_total`` — faults fired by the injection registry
- ``dyn_resume_attempts_total`` — mid-stream resume re-dispatches attempted
- ``dyn_resume_success_total``  — streams completed after >= 1 resume
- ``dyn_resume_prefill_requeues_total`` — disagg prefill work re-enqueued
- ``dyn_drain_started_total``   — worker drains initiated
- ``dyn_drain_completed_total`` — worker drains finished inside the budget
- ``dyn_drain_handoff_total``   — in-flight requests handed off by a drain
"""

from __future__ import annotations

import threading

HELP = {
    "dyn_cp_reconnects_total": "Control-plane connections re-established after loss",
    "dyn_retries_total": "Requests safely re-dispatched after a pre-first-token stream failure",
    "dyn_shed_total": "Requests shed (429/503) by frontend admission control",
    "dyn_faults_injected_total": "Faults fired by the DYN_FAULTS injection registry",
    "dyn_resume_attempts_total": "Mid-stream resume re-dispatches after a post-first-token failure",
    "dyn_resume_success_total": "Streams completed exactly-once after at least one mid-stream resume",
    "dyn_resume_prefill_requeues_total": "Disagg prefill work re-enqueued after a mid-KV-stream loss",
    "dyn_drain_started_total": "Worker graceful drains initiated (dynctl drain / SIGTERM / scale-down)",
    "dyn_drain_completed_total": "Worker graceful drains that emptied within the budget",
    "dyn_drain_handoff_total": "In-flight requests handed off (resume-redispatch) by a draining worker",
    "dyn_migration_started_total": "Live session migrations that passed validation and began the handoff",
    "dyn_migration_committed_total": "Live session migrations whose stream flip committed on the destination",
    "dyn_migration_aborted_total": "Migrations aborted cleanly back to the still-decoding source",
    "dyn_migration_failed_total": "Migrate requests rejected before any handoff started (unknown session, bad destination, unpriced DCN hop)",
    "dyn_migration_hidden_seconds": "Wall seconds of source decode overlapped with migration handoffs (latency hidden from clients)",
}

_lock = threading.Lock()
_counters: dict[str, int] = {}


def incr(name: str, by: float = 1) -> float:
    with _lock:
        _counters[name] = _counters.get(name, 0) + by
        return _counters[name]


def get(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def snapshot() -> dict[str, int]:
    """All known families plus any ad-hoc names that have been bumped."""
    with _lock:
        out = {name: 0 for name in HELP}
        out.update(_counters)
        return out


def reset() -> None:
    with _lock:
        _counters.clear()


def render() -> bytes:
    """Prometheus text exposition of every counter (known families always
    present so scrape checks can assert on them before the first event)."""
    lines = []
    for name, value in sorted(snapshot().items()):
        # accumulated-seconds families (e.g. dyn_migration_hidden_seconds)
        # render as gauges: the counter type reserves the _total suffix
        mtype = "counter" if name.endswith("_total") else "gauge"
        lines.append(f"# HELP {name} {HELP.get(name, 'Resilience counter')}")
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name} {value}")
    return ("\n".join(lines) + "\n").encode()
