"""Deterministic fault injection at the runtime's seams.

A process-global registry of named *fault points* checked inline on the
paths that matter for self-healing — control-plane RPC send/recv, data-plane
connect-back and mid-stream writes, KV transfer, engine step, prefill
dequeue.  Unarmed, a check is a dict lookup on an empty dict; armed, a
triggered check raises the configured exception with an ``injected fault``
marker in the message so chaos runs are diagnosable from logs alone.

Arming (``DYN_FAULTS`` env var or :meth:`FaultRegistry.arm`):

    DYN_FAULTS="cp.recv:once;worker.generate:nth=2;dp.send:prob=0.05:seed=7"

Grammar: ``;``-separated entries of ``point:trigger[:opt=val...]``.

Triggers (all deterministic — chaos tests are ordinary pytest):

- ``once``    — fire on the first check of the point, then disarm
- ``nth=N``   — fire on exactly the Nth check (1-based), then disarm
- ``every=N`` — fire on every Nth check
- ``prob=P``  — fire with probability P per check, from a seeded RNG
                (``seed=S`` option, default 0) so a given schedule replays
                identically

Options: ``exc=Name`` picks the raised type from :data:`EXCEPTIONS`
(default ``ConnectionError``); ``times=K`` caps total fires for
``every``/``prob`` triggers.
"""

from __future__ import annotations

import os
import random
import threading

from dynamo_tpu.robustness import counters
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("robustness.faults")

# The canonical fault-point names (call sites reference these constants so
# a typo is an import error, not a silently-never-firing fault).
CP_SEND = "cp.send"                  # control-plane RPC about to be written
CP_RECV = "cp.recv"                  # control-plane frame just received
DP_CONNECT = "dp.connect"            # worker data-plane connect-back dial
DP_SEND = "dp.send"                  # worker mid-stream response write
WORKER_GENERATE = "worker.generate"  # ingress handing a request to its engine
ENGINE_STEP = "engine.step"          # engine device-loop iteration
PREFILL_DEQUEUE = "prefill.dequeue"  # disagg prefill worker queue pop
KV_TRANSFER = "kv.transfer"          # disagg KV block shipment
MIGRATE_HANDOFF = "migrate.handoff"  # migration snapshot/KV-stream/pre-admit
MIGRATE_FLIP = "migrate.flip"        # migration stream flip about to commit

EXCEPTIONS: dict[str, type[BaseException]] = {
    "ConnectionError": ConnectionError,
    "ConnectionResetError": ConnectionResetError,
    "TimeoutError": TimeoutError,
    "OSError": OSError,
    "RuntimeError": RuntimeError,
}


class FaultSpec:
    """One armed fault point: trigger state + exception to raise."""

    def __init__(self, point: str, trigger: str, opts: dict[str, str]):
        self.point = point
        self.trigger = trigger
        self.exc_type = EXCEPTIONS[opts.get("exc", "ConnectionError")]
        self.checks = 0
        self.fires = 0
        self.max_fires = int(opts["times"]) if "times" in opts else None
        self.nth = 0
        self.every = 0
        self.prob = 0.0
        self._rng: random.Random | None = None
        if trigger == "once":
            self.nth = 1
        elif trigger.startswith("nth="):
            self.nth = int(trigger[4:])
            if self.nth < 1:
                raise ValueError(f"nth must be >= 1 in fault {point!r}")
        elif trigger.startswith("every="):
            self.every = int(trigger[6:])
            if self.every < 1:
                raise ValueError(f"every must be >= 1 in fault {point!r}")
        elif trigger.startswith("prob="):
            self.prob = float(trigger[5:])
            self._rng = random.Random(int(opts.get("seed", "0")))
        else:
            raise ValueError(f"unknown fault trigger {trigger!r} for {point!r}")

    @property
    def spent(self) -> bool:
        """True once this spec can never fire again (prune it)."""
        if self.nth:
            return self.fires > 0 or self.checks >= self.nth
        return self.max_fires is not None and self.fires >= self.max_fires

    def should_fire(self) -> bool:
        self.checks += 1
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.nth:
            return self.checks == self.nth and self.fires == 0
        if self.every:
            return self.checks % self.every == 0
        assert self._rng is not None
        return self._rng.random() < self.prob


def parse_faults(schedule: str) -> list[FaultSpec]:
    """Parse a ``DYN_FAULTS`` schedule string into specs."""
    specs = []
    for raw in schedule.replace(",", ";").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"bad fault entry {entry!r} (want point:trigger[:opt=val...])"
            )
        point, trigger = parts[0], parts[1]
        opts: dict[str, str] = {}
        for opt in parts[2:]:
            key, _, value = opt.partition("=")
            if not value:
                raise ValueError(f"bad fault option {opt!r} in {entry!r}")
            opts[key] = value
        specs.append(FaultSpec(point, trigger, opts))
    return specs


class FaultRegistry:
    """Thread-safe registry; the engine device thread checks it too."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._specs: dict[str, list[FaultSpec]] = {}
        self.fired: dict[str, int] = {}

    @property
    def armed(self) -> bool:
        return bool(self._specs)

    def arm(self, schedule: str) -> None:
        """Arm (additively) every entry of a schedule string."""
        for spec in parse_faults(schedule):
            with self._lock:
                self._specs.setdefault(spec.point, []).append(spec)

    def arm_from_env(self) -> None:
        schedule = knobs.get("DYN_FAULTS")
        if schedule:
            self.arm(schedule)

    def reset(self) -> None:
        with self._lock:
            self._specs.clear()
            self.fired.clear()

    def check(self, point: str, **attrs) -> None:
        """Raise iff an armed spec for ``point`` triggers.  The no-fault
        path is one dict lookup — cheap enough for per-frame call sites."""
        specs = self._specs.get(point)
        if not specs:
            return
        with self._lock:
            fire: FaultSpec | None = None
            for spec in specs:
                if spec.should_fire():
                    fire = spec
                    break
            if fire is not None:
                fire.fires += 1
                self.fired[point] = self.fired.get(point, 0) + 1
            # prune spent specs so disarmed points return to the fast path
            live = [s for s in specs if not s.spent]
            if live:
                self._specs[point] = live
            else:
                self._specs.pop(point, None)
            if fire is None:
                return
        counters.incr("dyn_faults_injected_total")
        detail = "".join(f" {k}={v}" for k, v in attrs.items())
        logger.warning("injected fault at %s (#%d)%s", point, self.fired[point], detail)
        try:
            # flight recorder: injected faults are exactly the discrete
            # events a post-mortem wants time-aligned with step telemetry.
            # Lazy import (faults sits below observability in the graph).
            from dynamo_tpu.observability import flight

            for rec in flight.recorders():
                rec.record_event("fault", point=point, fire=self.fired[point])
        except Exception:  # noqa: BLE001 — never mask the injected fault
            pass
        raise fire.exc_type(f"injected fault at {point} (#{self.fired[point]})")


# Process-global registry, armed from DYN_FAULTS at import (tests arm/reset
# it directly).
FAULTS = FaultRegistry()
FAULTS.arm_from_env()


def get_faults() -> FaultRegistry:
    return FAULTS
