"""Frontend admission control: bounded in-flight + bounded wait queue.

Overload policy (load-shedding beats timing out: a client told 429/503 with
``Retry-After`` can back off; a client waiting out a 120s socket timeout
cannot):

- up to ``max_inflight`` requests are admitted immediately;
- the next ``max_queue_depth`` wait up to ``queue_timeout_s`` for capacity;
- beyond the queue watermark → **429** at once (the burst is oversized);
- a queued request whose wait expires → **503** (the backlog is not
  draining — the fleet is saturated, not merely bursty).

Both sheds carry ``Retry-After`` and bump ``dyn_shed_total``.  Disabled
(the default, ``max_inflight == 0``) every call is a no-op.

SLO hook: when the HTTP frontend wires a ``burn_rate_fn`` (the SLO
tracker's worst burn rate, observability/slo.py) and
``shed_burn_threshold`` > 0 (``DYN_SLO_SHED_BURN``), a saturated gate stops
queueing while the error budget is burning past the threshold — queueing
deeper during a burn converts future 200s into future SLO violations.
"""

from __future__ import annotations

import asyncio
import os
from dataclasses import dataclass

from dynamo_tpu.robustness import counters
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("robustness.admission")


class Overloaded(Exception):
    """Request shed by admission control."""

    def __init__(self, status: int, message: str, retry_after_s: float):
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s


@dataclass
class AdmissionConfig:
    max_inflight: int = 0  # 0 = admission control disabled
    max_queue_depth: int = 0
    queue_timeout_s: float = 2.0
    retry_after_s: float = 1.0

    @classmethod
    def from_env(cls) -> "AdmissionConfig":
        max_inflight = knobs.get("DYN_ADMISSION_MAX_INFLIGHT")
        queue_depth = knobs.get("DYN_ADMISSION_QUEUE")
        return cls(
            max_inflight=max_inflight,
            max_queue_depth=(
                queue_depth if queue_depth is not None else 2 * max_inflight
            ),
            queue_timeout_s=knobs.get("DYN_ADMISSION_QUEUE_TIMEOUT_S"),
            retry_after_s=knobs.get("DYN_ADMISSION_RETRY_AFTER_S"),
        )


class AdmissionController:
    """Counting admission gate for one HTTP frontend process."""

    def __init__(self, config: AdmissionConfig | None = None):
        self.config = config or AdmissionConfig.from_env()
        self._cond = asyncio.Condition()
        self._inflight = 0
        self._queued = 0
        self.shed_total = 0
        # SLO consult (set by the frontend): () -> current worst burn rate.
        # 0 threshold = hook disabled.
        self.burn_rate_fn = None
        self.shed_burn_threshold = 0.0

    def _burning(self) -> float | None:
        """Current burn rate when it exceeds the shed threshold, else None."""
        if self.shed_burn_threshold <= 0 or self.burn_rate_fn is None:
            return None
        try:
            burn = float(self.burn_rate_fn())
        except Exception:  # noqa: BLE001 — telemetry must never fail admission
            return None
        return burn if burn >= self.shed_burn_threshold else None

    @property
    def enabled(self) -> bool:
        return self.config.max_inflight > 0

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def queue_depth(self) -> int:
        return self._queued

    def _shed(self, status: int, reason: str) -> Overloaded:
        self.shed_total += 1
        counters.incr("dyn_shed_total")
        logger.warning(
            "shedding request (%s): inflight=%d queued=%d",
            reason, self._inflight, self._queued,
        )
        return Overloaded(
            status,
            f"server overloaded ({reason}); retry after "
            f"{self.config.retry_after_s:g}s",
            self.config.retry_after_s,
        )

    async def acquire(self) -> None:
        """Admit or raise :class:`Overloaded`.  Callers MUST pair a
        successful acquire with exactly one :meth:`release`."""
        if not self.enabled:
            return
        cfg = self.config
        async with self._cond:
            if self._inflight < cfg.max_inflight:
                self._inflight += 1
                return
            burn = self._burning()
            if burn is not None:
                raise self._shed(429, f"slo burn rate {burn:.2f}")
            if self._queued >= cfg.max_queue_depth:
                raise self._shed(429, "queue full")
            self._queued += 1
            try:
                loop = asyncio.get_running_loop()
                deadline = loop.time() + cfg.queue_timeout_s
                while self._inflight >= cfg.max_inflight:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        raise self._shed(503, "queue wait timed out")
                    try:
                        await asyncio.wait_for(self._cond.wait(), remaining)
                    except asyncio.TimeoutError:
                        raise self._shed(503, "queue wait timed out") from None
            except BaseException:
                # shed/cancelled while queued: on py<3.13 a cancelled
                # Condition.wait can swallow a notify that raced it
                # (gh-90155) — re-notify so the freed slot reaches another
                # queued waiter instead of idling until a new request
                self._cond.notify(1)
                raise
            finally:
                self._queued -= 1
            self._inflight += 1

    async def release(self) -> None:
        if not self.enabled:
            return
        async with self._cond:
            self._inflight -= 1
            self._cond.notify(1)
