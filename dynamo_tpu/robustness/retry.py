"""Exponential backoff with jitter (reconnects, resubscribes, retries).

(Reference posture: etcd/NATS clients reconnect forever with capped
exponential backoff; jitter keeps a restarted control plane from being
stampeded by every client retrying in phase.)
"""

from __future__ import annotations

import math
import os
import random


class Backoff:
    """Capped exponential backoff.  ``rng`` may be seeded for deterministic
    chaos tests.

    Two jitter modes:

    - *equal* (default): each delay is multiplied by ``1 ± jitter`` — the
      retry cadence stays recognizable in logs, but a fleet that failed in
      phase stays mostly in phase (±20 % of the same schedule).
    - *full* (``full_jitter=True``): each delay is drawn uniformly from
      ``[0, min(cap, initial·factor^n)]`` (AWS "full jitter") — the spread
      covers the whole interval, which is what actually de-synchronizes a
      reconnect storm across a fleet after a control-plane restart.
    """

    def __init__(
        self,
        initial: float = 0.05,
        factor: float = 2.0,
        max_delay: float = 2.0,
        jitter: float = 0.2,
        rng: random.Random | None = None,
        full_jitter: bool = False,
    ):
        self.initial = initial
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self.full_jitter = full_jitter
        self.attempts = 0
        self._rng = rng or random.Random()

    @classmethod
    def from_env(cls, prefix: str, **defaults) -> "Backoff":
        """Read ``{prefix}_BACKOFF_S`` / ``{prefix}_BACKOFF_MAX_S`` env
        overrides on top of ``defaults``."""
        initial = os.environ.get(f"{prefix}_BACKOFF_S")
        max_delay = os.environ.get(f"{prefix}_BACKOFF_MAX_S")
        if initial is not None:
            defaults["initial"] = float(initial)
        if max_delay is not None:
            defaults["max_delay"] = float(max_delay)
        return cls(**defaults)

    def _base(self) -> float:
        """``min(initial·factor^attempts, max_delay)`` without overflow: a
        long-lived reconnect loop (days of attempts) would otherwise crash
        in ``factor ** attempts`` — Python floats raise OverflowError around
        2.0**1024 — so the exponent is clamped to the smallest value whose
        uncapped delay already exceeds the cap (larger exponents cannot
        change the ``min``)."""
        exponent = self.attempts
        if self.factor > 1.0 and self.initial > 0:
            ceiling = math.log(
                max(self.max_delay / self.initial, 1.0), self.factor
            )
            exponent = min(exponent, int(ceiling) + 1)
        return min(self.initial * (self.factor ** exponent), self.max_delay)

    def next(self) -> float:
        delay = self._base()
        self.attempts += 1
        if self.full_jitter:
            return self._rng.uniform(0.0, delay)
        if self.jitter:
            delay *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(delay, 0.0)

    def reset(self) -> None:
        self.attempts = 0
