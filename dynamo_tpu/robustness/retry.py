"""Exponential backoff with jitter (reconnects, resubscribes, retries).

(Reference posture: etcd/NATS clients reconnect forever with capped
exponential backoff; jitter keeps a restarted control plane from being
stampeded by every client retrying in phase.)
"""

from __future__ import annotations

import os
import random


class Backoff:
    """Capped exponential backoff.  ``rng`` may be seeded for deterministic
    chaos tests; jitter multiplies each delay by ``1 ± jitter``."""

    def __init__(
        self,
        initial: float = 0.05,
        factor: float = 2.0,
        max_delay: float = 2.0,
        jitter: float = 0.2,
        rng: random.Random | None = None,
    ):
        self.initial = initial
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self.attempts = 0
        self._rng = rng or random.Random()

    @classmethod
    def from_env(cls, prefix: str, **defaults) -> "Backoff":
        """Read ``{prefix}_BACKOFF_S`` / ``{prefix}_BACKOFF_MAX_S`` env
        overrides on top of ``defaults``."""
        initial = os.environ.get(f"{prefix}_BACKOFF_S")
        max_delay = os.environ.get(f"{prefix}_BACKOFF_MAX_S")
        if initial is not None:
            defaults["initial"] = float(initial)
        if max_delay is not None:
            defaults["max_delay"] = float(max_delay)
        return cls(**defaults)

    def next(self) -> float:
        delay = min(self.initial * (self.factor ** self.attempts), self.max_delay)
        self.attempts += 1
        if self.jitter:
            delay *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(delay, 0.0)

    def reset(self) -> None:
        self.attempts = 0
