"""Component model: Namespace → Component → Endpoint → Instance.

Mirrors the reference's hierarchy and etcd layout (reference:
lib/runtime/src/component.rs:70-133): instances register under
``dynamo://{ns}/components/{comp}/endpoints/{ep}/instances/{id}`` with a
liveness lease; clients watch the prefix and fail over when leases lapse.
"""

from __future__ import annotations

import asyncio
import json
import secrets
from dataclasses import dataclass
from typing import TYPE_CHECKING

from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.utils.logging import get_logger

if TYPE_CHECKING:
    from dynamo_tpu.runtime.client import Client
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.ingress import EndpointService

logger = get_logger("runtime.component")

ROOT_PATH = "dynamo://"


@dataclass(frozen=True)
class Instance:
    """A live endpoint instance (one worker process serving one endpoint)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int
    subject: str

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "namespace": self.namespace,
                "component": self.component,
                "endpoint": self.endpoint,
                "instance_id": self.instance_id,
                "subject": self.subject,
            }
        ).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Instance":
        d = json.loads(data)
        return cls(
            namespace=d["namespace"],
            component=d["component"],
            endpoint=d["endpoint"],
            instance_id=d["instance_id"],
            subject=d["subject"],
        )


def instances_prefix(namespace: str, component: str, endpoint: str) -> str:
    return f"{ROOT_PATH}{namespace}/components/{component}/endpoints/{endpoint}/instances/"


def instance_key(inst: Instance) -> str:
    return instances_prefix(inst.namespace, inst.component, inst.endpoint) + f"{inst.instance_id:016x}"


def endpoint_subject(namespace: str, component: str, endpoint: str, instance_id: int) -> str:
    return f"{namespace}.{component}.{endpoint}.{instance_id:x}"


def stats_subject(subject: str) -> str:
    """Request/reply subject for per-instance stats scraping (the reference's
    NATS ``$SRV`` service-stats analog, lib/runtime/src/service.rs)."""
    return f"_stats.{subject}"


def ctl_subject(subject: str) -> str:
    """Request/reply subject for per-instance control verbs (drain)."""
    return f"_ctl.{subject}"


class Namespace:
    def __init__(self, runtime: "DistributedRuntime", name: str):
        self.runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    async def delete(self) -> int:
        """Tear down everything registered under this namespace."""
        return await self.runtime.plane.kv.delete_prefix(f"{ROOT_PATH}{self.name}/")


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name

    @property
    def runtime(self) -> "DistributedRuntime":
        return self.namespace.runtime

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    def event_subject(self, event: str) -> str:
        """Component-scoped event subject (e.g. KV events; reference:
        lib/llm/src/kv_router.rs:43)."""
        return f"{self.namespace.name}.{self.name}._events.{event}"


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name

    @property
    def runtime(self) -> "DistributedRuntime":
        return self.component.runtime

    @property
    def path(self) -> str:
        return f"{self.component.namespace.name}.{self.component.name}.{self.name}"

    async def serve(
        self,
        engine: AsyncEngine,
        *,
        instance_id: int | None = None,
        lease_ttl: float = 3.0,
        stats_handler=None,
        topo_role: str = "",
        topo_transfer_address: str = "",
        topo_slice: str | None = None,
    ) -> "EndpointService":
        """Register an instance and start serving requests pushed to it.

        ``topo_*`` feed the instance's TopologyCard (fleet topology plane):
        role (``prefill``/``decode``), the KV-transfer data-plane address,
        and an explicit slice label for emulated multi-slice fleets.
        """
        from dynamo_tpu.runtime.ingress import EndpointService

        inst_id = instance_id if instance_id is not None else secrets.randbits(63)
        instance = Instance(
            namespace=self.component.namespace.name,
            component=self.component.name,
            endpoint=self.name,
            instance_id=inst_id,
            subject=endpoint_subject(
                self.component.namespace.name, self.component.name, self.name, inst_id
            ),
        )
        service = EndpointService(
            self.runtime, instance, engine, stats_handler=stats_handler,
            topo_role=topo_role, topo_transfer_address=topo_transfer_address,
            topo_slice=topo_slice,
        )
        await service.start(lease_ttl=lease_ttl)
        return service

    async def client(self, *, static_instances: list[Instance] | None = None) -> "Client":
        from dynamo_tpu.runtime.client import Client

        client = Client(self.runtime, self, static_instances=static_instances)
        await client.start()
        return client
