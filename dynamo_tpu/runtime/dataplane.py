"""Data plane: direct TCP response streams.

Requests ride the control-plane bus (push), responses ride a direct TCP
byte-stream from worker back to caller — the reference's split transport
design (reference: lib/runtime/src/pipeline/network/egress/addressed_router.rs:59-65,
tcp/server.rs).

- ``ResponseStreamServer`` (caller side): ``register(stream_id)`` a pending
  stream before publishing the request; the worker connects back, sends a
  prologue identifying the stream, then pumps data frames.  The caller can
  send ``stop``/``kill`` control frames upstream on the same connection.
- ``ResponseStreamSender`` (worker side): connect-back handle that sends the
  prologue, streams responses, and surfaces incoming control frames on the
  request's EngineContext.

Frame headers: ``{"t": "prologue"|"data"|"complete"|"error"|"stop"|"kill"}``.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

import msgpack

from dynamo_tpu.robustness.faults import DP_CONNECT, DP_SEND, FAULTS
from dynamo_tpu.runtime.codec import (
    TwoPartMessage,
    attach_trace,
    encode_frame,
    extract_trace,
    read_two_part,
)
from dynamo_tpu.runtime.engine import EngineContext
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("runtime.dataplane")

STREAM_TIMEOUT = 600.0  # max seconds a registered stream waits for connect-back
READ_CHUNK = 1 << 16


async def iter_frames(reader: asyncio.StreamReader):
    """Yield two-part frames until EOF.

    Uses the native incremental decoder when available — one socket read per
    chunk with frame splitting in C++, instead of three awaits per frame —
    which matters on the per-token response hot path.  Falls back to the
    pure-Python codec."""
    decoder = None
    try:
        from dynamo_tpu.native.dataplane import NativeFrameDecoder

        decoder = NativeFrameDecoder()
    except RuntimeError:
        pass
    if decoder is None:
        while True:
            frame = await read_two_part(reader)
            if frame is None:
                return
            yield frame
    else:
        while True:
            try:
                chunk = await reader.read(READ_CHUNK)
            except ConnectionResetError:
                return  # same "connection lost" semantics as read_two_part
            if not chunk:
                return
            decoder.feed(chunk)
            for msg in decoder.drain():  # one C call per chunk
                yield msg


@dataclass
class ConnectionInfo:
    """Where the worker should connect back to (carried in the request
    control message, like the reference's ``connection_info``)."""

    host: str
    port: int
    stream_id: str

    def to_dict(self) -> dict:
        return {"host": self.host, "port": self.port, "stream_id": self.stream_id}

    @classmethod
    def from_dict(cls, d: dict) -> "ConnectionInfo":
        return cls(host=d["host"], port=d["port"], stream_id=d["stream_id"])


class PendingStream:
    """A registered response stream awaiting connect-back, then pumping items."""

    def __init__(self, stream_id: str, ctx: EngineContext):
        self.stream_id = stream_id
        self.ctx = ctx
        self.queue: asyncio.Queue[dict | None] = asyncio.Queue()
        self.connected = asyncio.Event()
        self.error: str | None = None
        # the worker's trace context from the connect-back prologue (None
        # until connected / when the worker is untraced)
        self.trace = None
        self._writer: asyncio.StreamWriter | None = None

    async def send_control(self, kind: str) -> None:
        if self._writer is None or self._writer.is_closing():
            return
        try:
            self._writer.write(encode_frame(TwoPartMessage(header={"t": kind})))
            await self._writer.drain()
        except (ConnectionError, RuntimeError):
            pass

    def __aiter__(self):
        return self

    async def __anext__(self) -> dict:
        item = await self.queue.get()
        if item is None:
            if self.error:
                raise RuntimeError(f"remote engine error: {self.error}")
            raise StopAsyncIteration
        return item


class ResponseStreamServer:
    """Caller-side TCP server that response streams rendezvous with."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._pending: dict[str, PendingStream] = {}
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        if self._server is not None:
            return
        # warm the native codec off-loop: first use otherwise triggers a
        # synchronous g++ compile inside a connection handler
        from dynamo_tpu.native import load_native

        await asyncio.to_thread(load_native, "dataplane")
        # backlog: asyncio's default (100) overflows under request bursts —
        # a few hundred concurrent generates all dial connect-backs at
        # once, the kernel RSTs the overflow, and those requests die
        # (found by the runtime soak test)
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, backlog=1024
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.debug("response stream server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def register(self, stream_id: str, ctx: EngineContext) -> PendingStream:
        stream = PendingStream(stream_id, ctx)
        self._pending[stream_id] = stream
        return stream

    def unregister(self, stream_id: str) -> None:
        self._pending.pop(stream_id, None)

    def connection_info(self, stream_id: str) -> ConnectionInfo:
        return ConnectionInfo(host=self.host, port=self.port, stream_id=stream_id)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        stream: PendingStream | None = None
        control_task: asyncio.Task | None = None
        try:
            prologue = await read_two_part(reader)
            if prologue is None or prologue.header.get("t") != "prologue":
                writer.close()
                return
            stream_id = prologue.header["stream_id"]
            stream = self._pending.get(stream_id)
            if stream is None:
                writer.write(encode_frame(TwoPartMessage(header={"t": "kill"})))
                await writer.drain()
                writer.close()
                return
            stream.trace = extract_trace(prologue.header)
            stream._writer = writer
            stream.connected.set()

            # forward caller-side cancellation upstream
            async def watch_cancel() -> None:
                await stream.ctx.stopped()
                await stream.send_control("kill" if stream.ctx.is_killed else "stop")

            control_task = spawn_logged(watch_cancel())

            finished = False
            async for frame in iter_frames(reader):
                kind = frame.header.get("t")
                if kind == "data":
                    stream.queue.put_nowait(msgpack.unpackb(frame.payload, raw=False))
                elif kind == "complete":
                    finished = True
                    break
                elif kind == "error":
                    stream.error = frame.header.get("message", "unknown remote error")
                    finished = True
                    break
            if not finished:
                stream.error = stream.error or "connection lost"
        finally:
            if control_task is not None:
                control_task.cancel()
            if stream is not None:
                self._pending.pop(stream.stream_id, None)
                stream.queue.put_nowait(None)
            writer.close()


class ResponseStreamSender:
    """Worker-side connect-back sender."""

    def __init__(self, info: ConnectionInfo, ctx: EngineContext):
        self.info = info
        self.ctx = ctx
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._control_task: asyncio.Task | None = None

    async def connect(self, attempts: int = 5) -> None:
        # chaos seam: a worker that dies before dialing back (the frontend
        # sees a rendezvous timeout and fails over)
        FAULTS.check(DP_CONNECT, stream=self.info.stream_id)
        # bounded retry: under a connect burst the frontend's accept queue
        # can momentarily overflow and the kernel RSTs the dial; without a
        # retry that request is silently lost and the frontend waits out
        # its rendezvous timeout (found by the runtime soak test)
        delay = 0.05
        for attempt in range(attempts):
            try:
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(self.info.host, self.info.port),
                    timeout=5.0,
                )
                break
            except (OSError, asyncio.TimeoutError):
                if attempt + 1 == attempts:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)
        # the prologue carries the worker-side trace context so the caller
        # can correlate this byte stream with the request's span tree
        header = attach_trace(
            {"t": "prologue", "stream_id": self.info.stream_id},
            getattr(self.ctx, "trace", None),
        )
        self._writer.write(encode_frame(TwoPartMessage(header=header)))
        await self._writer.drain()
        self._control_task = spawn_logged(self._control_loop())

    async def _control_loop(self) -> None:
        """Surface caller stop/kill on the worker-side context."""
        assert self._reader is not None
        while True:
            frame = await read_two_part(self._reader)
            if frame is None:
                # caller went away: treat as kill so the engine stops work
                self.ctx.kill()
                return
            kind = frame.header.get("t")
            if kind == "stop":
                self.ctx.stop_generating()
            elif kind == "kill":
                self.ctx.kill()
                return

    async def send(self, item: dict) -> None:
        # chaos seam: a mid-stream write failure (worker killed while
        # streaming; pre-first-token it is retried, after it truncates)
        FAULTS.check(DP_SEND, stream=self.info.stream_id)
        assert self._writer is not None
        self._writer.write(
            encode_frame(
                TwoPartMessage(header={"t": "data"}, payload=msgpack.packb(item, use_bin_type=True))
            )
        )
        await self._writer.drain()

    async def complete(self) -> None:
        await self._finish({"t": "complete"})

    async def error(self, message: str) -> None:
        await self._finish({"t": "error", "message": message})

    async def _finish(self, header: dict) -> None:
        if self._control_task is not None:
            self._control_task.cancel()
        if self._writer is None or self._writer.is_closing():
            return
        try:
            self._writer.write(encode_frame(TwoPartMessage(header=header)))
            await self._writer.drain()
        except ConnectionError:
            pass
        finally:
            self._writer.close()
