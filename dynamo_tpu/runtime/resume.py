"""Mid-stream resumable generation: the journal + resume wire protocol.

When a worker dies after the first token, the dispatch path used to truncate
the stream (PR 3 made first-token the retry boundary).  This module is the
seam that moves the boundary: the dispatcher keeps a per-request
:class:`GenerationJournal` (prompt hash + every token the client has been
shown + the sampling state that makes replay deterministic), and on a
mid-stream transport failure re-dispatches the *original* request with a
``resume_from`` payload attached.

Two ways a fresh worker can honor it — negotiated per-stream, not per-fleet:

- **Replay (default, engine-agnostic).**  An engine that has never heard of
  ``resume_from`` simply replays the request from token zero
  (``PreprocessedRequest.from_wire`` ignores unknown keys).  The
  dispatcher-side :func:`dedupe_stream` cursor drops exactly the first
  ``len(accepted)`` generated tokens, so the client stream is byte-identical
  under greedy decoding and replay-identical under seeded sampling.  The
  replayed prefix rides the radix/prefix-cache paths, so it is usually a
  cache hit, not recomputation.
- **Continuation (resume-aware engines).**  An engine that calls
  :func:`apply_resume` extends the prompt with the accepted tokens, shrinks
  ``max_tokens`` accordingly, and emits :func:`ack_item` as the FIRST stream
  item.  The cursor sees the ack, drops nothing, and swallows the ack before
  it can reach the client.

Resume is only offered for requests whose replay is deterministic: greedy
(``use_greedy``, or temperature unset/<= 0 — the same predicate the engines
use) or explicitly seeded.  Anything else keeps today's behavior: an honest
truncation error instead of silently divergent text.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, AsyncIterator

from dynamo_tpu.utils import knobs

# Annotation event a resume-aware engine emits as its first item to signal
# "I continued from your accepted tokens — nothing to dedupe".
RESUME_ACK_EVENT = "dyn.resume.ack"


def _is_deterministic(sampling: dict) -> bool:
    """Same greedy predicate the engines apply (engine.py / mocker), plus
    explicit seeding: either way a replay reproduces the accepted prefix."""
    if sampling.get("use_greedy"):
        return True
    if sampling.get("seed") is not None:
        return True
    temperature = sampling.get("temperature")
    return temperature is None or temperature <= 0.0


class GenerationJournal:
    """Everything needed to resume one in-flight generation elsewhere.

    Records at the *yield* point — the accepted list is exactly the tokens
    the caller has observed, so a second failure mid-resumed-stream resumes
    from the grown cursor, and ``resume_request`` is always built against
    the original wire request captured at construction.
    """

    def __init__(self, wire_request: dict):
        self.request = wire_request
        token_ids = wire_request.get("token_ids") or []
        self.prompt_hash = hashlib.sha256(
            json.dumps(list(token_ids)).encode()
        ).hexdigest()
        sampling = wire_request.get("sampling") or {}
        self.sampling = {
            k: sampling.get(k) for k in ("use_greedy", "seed", "temperature")
            if sampling.get(k) is not None
        }
        # only LLM wire requests (token_ids present) are resumable: for an
        # arbitrary endpoint payload a replay would duplicate stream items
        # the dedupe cursor cannot see
        self.resumable = isinstance(
            wire_request.get("token_ids"), list
        ) and _is_deterministic(sampling)
        self.accepted: list[int] = []
        self.resumes = 0
        # memory bound: accepted tokens beyond this fold into the base
        # prompt, so a very long stream's journal stays O(cap), not O(osl)
        self.max_items = knobs.get("DYN_RESUME_JOURNAL_MAX_ITEMS") or 0
        self.folded = 0
        self.finished = False

    @property
    def total_recorded(self) -> int:
        """Tokens recorded over the request's whole lifetime — fold-invariant,
        so migration snapshots can be diffed across a fold boundary."""
        return self.folded + len(self.accepted)

    def _fold(self, count: int) -> None:
        """Move the ``count`` oldest accepted tokens into the base prompt.

        A resume built afterwards replays/continues from the grown prompt
        with a correspondingly smaller accepted tail and max_tokens budget —
        semantically identical, just with the cursor's oldest prefix baked
        into ``token_ids``.  The captured request is never mutated in place;
        the journal swaps in a rewritten copy."""
        if count <= 0 or not self.resumable:
            return
        prefix, self.accepted = self.accepted[:count], self.accepted[count:]
        wire = dict(self.request)
        wire["token_ids"] = list(wire.get("token_ids") or []) + prefix
        stop = dict(wire.get("stop") or {})
        max_tokens = stop.get("max_tokens")
        if max_tokens is not None:
            stop["max_tokens"] = max(int(max_tokens) - len(prefix), 1)
            wire["stop"] = stop
        self.request = wire
        self.folded += len(prefix)
        self.prompt_hash = hashlib.sha256(
            json.dumps(list(wire["token_ids"])).encode()
        ).hexdigest()

    def record(self, item: dict) -> None:
        """Note a wire item the caller is about to see (post-dedupe)."""
        if not isinstance(item, dict):
            return
        data = item.get("data")
        if isinstance(data, dict):
            self.accepted.extend(data.get("token_ids") or [])
            if self.max_items > 0 and len(self.accepted) > self.max_items:
                self._fold(len(self.accepted) - self.max_items)

    def finish(self) -> None:
        """The stream delivered its finish item: release the retained tokens
        now instead of waiting for the request object graph to die."""
        self.finished = True
        self.folded = self.total_recorded
        self.accepted = []

    def resume_payload(self) -> dict:
        # penalty counts / stop-sequence progress are a pure function of the
        # accepted ids, so shipping the ids ships that state too
        return {
            "v": 1,
            "prompt_hash": self.prompt_hash,
            "accepted": list(self.accepted),
            "sampling": dict(self.sampling),
        }

    def resume_request(self) -> dict:
        """The original wire request plus the resume cursor.  Unaware
        engines ignore the extra key and replay; aware engines continue."""
        wire = dict(self.request)
        wire["resume_from"] = self.resume_payload()
        return wire


def apply_resume(wire: dict) -> tuple[dict, int]:
    """Engine-side continuation: rewrite a ``resume_from`` request so the
    engine picks up where the dead worker stopped.

    Returns ``(request, accepted_count)``.  ``accepted_count == 0`` means no
    resume was requested (or nothing had been accepted — a plain replay is
    then identical to a fresh run).  When positive, the returned request has
    the accepted tokens appended to ``token_ids`` and ``max_tokens`` reduced
    to the remaining budget, and the engine MUST emit :func:`ack_item` as
    its first stream item so the dispatcher's cursor knows not to dedupe.
    """
    payload = wire.get("resume_from")
    if not isinstance(payload, dict):
        return wire, 0
    out = dict(wire)
    out.pop("resume_from", None)
    accepted = list(payload.get("accepted") or [])
    if not accepted:
        return out, 0
    out["token_ids"] = list(wire.get("token_ids") or []) + accepted
    stop = dict(out.get("stop") or {})
    max_tokens = stop.get("max_tokens")
    if max_tokens is not None:
        stop["max_tokens"] = max(int(max_tokens) - len(accepted), 1)
        out["stop"] = stop
    return out, len(accepted)


def ack_item(accepted_count: int) -> dict:
    """The wire item a continuation-mode engine emits first (an annotation:
    no ``data`` key, so nothing downstream mistakes it for tokens)."""
    return {
        "event": RESUME_ACK_EVENT,
        "comment": [json.dumps({"accepted": accepted_count})],
    }


async def dedupe_stream(
    stream: AsyncIterator[dict], skip: int, *, ack_skip: int = 0
) -> AsyncIterator[dict]:
    """Exactly-once cursor over a resumed stream.

    Replay mode: drop the first ``skip`` generated tokens (count-based — a
    new token that happens to equal an old one must NOT be dropped, so no
    content matching).  Continuation mode: the first item is a
    ``dyn.resume.ack`` annotation — swallow it, then drop ``ack_skip``
    tokens.  A plain resume leaves ``ack_skip`` at 0 (the continuation
    starts exactly at the cursor); a live-migration handoff passes the
    tokens the *source kept decoding* between the journal snapshot shipped
    to the destination and the flip commit — the destination regenerates
    that window, and dropping it is what makes the flip exactly-once.  A
    finish_reason landing inside the dropped prefix is preserved on an
    empty-token item so the stream still terminates cleanly.
    """
    first = True
    remaining = skip
    async for item in stream:
        if first:
            first = False
            if isinstance(item, dict) and item.get("event") == RESUME_ACK_EVENT:
                remaining = ack_skip
                continue
        if remaining > 0 and isinstance(item, dict):
            data = item.get("data")
            if isinstance(data, dict):
                tokens = data.get("token_ids") or []
                if tokens:
                    if len(tokens) <= remaining:
                        remaining -= len(tokens)
                        if data.get("finish_reason"):
                            rewritten: dict[str, Any] = dict(item)
                            rewritten["data"] = {**data, "token_ids": []}
                            yield rewritten
                        continue
                    rewritten = dict(item)
                    rewritten["data"] = {**data, "token_ids": tokens[remaining:]}
                    remaining = 0
                    yield rewritten
                    continue
        yield item
