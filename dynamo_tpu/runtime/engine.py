"""Streaming engine abstraction.

The universal building block of the framework, mirroring the reference's
``AsyncEngine`` trait and ``Context`` envelope (reference:
lib/runtime/src/engine.rs:46-110, lib/runtime/src/pipeline/context.rs):

- ``EngineContext``  — per-request identity + two-phase cancellation
  (``stop_generating`` = stop issuing new tokens gracefully, ``kill`` = abort).
- ``Context[T]``     — a request ``T`` wrapped with its ``EngineContext``;
  ``map`` transforms the payload while *transferring* the context.
- ``AsyncEngine``    — ``generate(Context[Req]) -> ResponseStream[Resp]``.
- ``ResponseStream`` — an async iterator of responses paired with the context.
- ``Operator``       — a bidirectional pipeline stage that transforms the
  request on the way in and the response stream on the way out (how the
  preprocessor/detokenizer compose around a backend engine; reference:
  lib/runtime/src/pipeline/nodes.rs).
"""

from __future__ import annotations

import asyncio
import uuid
from abc import ABC, abstractmethod
from typing import Any, AsyncIterator, Callable, Generic, Protocol, TypeVar

T = TypeVar("T")
U = TypeVar("U")
Req = TypeVar("Req")
Resp = TypeVar("Resp")


class EngineContext:
    """Identity + cancellation state for one in-flight request."""

    def __init__(self, request_id: str | None = None):
        self.id: str = request_id or uuid.uuid4().hex
        # distributed tracing context (observability.trace.TraceContext |
        # None): set by whoever minted this request (HTTP frontend) or
        # decoded it off the wire (ingress); rides Context.map/transfer for
        # free since the EngineContext object itself is transferred
        self.trace = None
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: list[EngineContext] = []

    # --- cancellation -----------------------------------------------------
    def stop_generating(self) -> None:
        """Gracefully stop producing new output (finish current token)."""
        self._stopped.set()
        for child in self._children:
            child.stop_generating()

    def kill(self) -> None:
        """Abort the request immediately."""
        self._killed.set()
        self._stopped.set()
        for child in self._children:
            child.kill()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def stopped(self) -> None:
        await self._stopped.wait()

    async def killed(self) -> None:
        await self._killed.wait()

    def link_child(self, child: "EngineContext") -> None:
        """Propagate cancellation to a downstream context."""
        self._children.append(child)
        if self.is_killed:
            child.kill()
        elif self.is_stopped:
            child.stop_generating()


class Context(Generic[T]):
    """A request payload travelling with its EngineContext (``SingleIn<T>``)."""

    __slots__ = ("data", "ctx")

    def __init__(self, data: T, ctx: EngineContext | None = None):
        self.data = data
        self.ctx = ctx or EngineContext()

    @property
    def id(self) -> str:
        return self.ctx.id

    def map(self, fn: Callable[[T], U]) -> "Context[U]":
        """Transform the payload, transferring the context."""
        return Context(fn(self.data), self.ctx)

    def transfer(self, data: U) -> "Context[U]":
        return Context(data, self.ctx)

    def __repr__(self) -> str:
        return f"Context(id={self.ctx.id[:8]}, data={type(self.data).__name__})"


class ResponseStream(Generic[T]):
    """``ManyOut<T>``: an async response iterator paired with its context."""

    def __init__(self, stream: AsyncIterator[T], ctx: EngineContext):
        self._stream = stream
        self.ctx = ctx

    def __aiter__(self) -> AsyncIterator[T]:
        return self._stream.__aiter__()

    async def __anext__(self) -> T:
        return await self._stream.__anext__()

    def map(self, fn: Callable[[T], U]) -> "ResponseStream[U]":
        async def _mapped() -> AsyncIterator[U]:
            async for item in self._stream:
                yield fn(item)

        return ResponseStream(_mapped(), self.ctx)

    @classmethod
    def from_items(cls, items: list[T], ctx: EngineContext) -> "ResponseStream[T]":
        async def _gen() -> AsyncIterator[T]:
            for item in items:
                yield item

        return cls(_gen(), ctx)

    async def collect(self) -> list[T]:
        return [item async for item in self]


class AsyncEngine(Protocol[Req, Resp]):
    """The universal streaming-engine interface."""

    async def generate(self, request: Context[Req]) -> ResponseStream[Resp]:
        ...


class FnEngine(Generic[Req, Resp]):
    """Adapt ``async def fn(request, ctx) -> AsyncIterator`` into an engine."""

    def __init__(self, fn: Callable[[Req, EngineContext], AsyncIterator[Resp]]):
        self._fn = fn

    async def generate(self, request: Context[Req]) -> ResponseStream[Resp]:
        return ResponseStream(self._fn(request.data, request.ctx), request.ctx)


class Operator(ABC, Generic[Req, Resp]):
    """A bidirectional pipeline stage.

    ``preprocess`` maps the incoming request to the inner request type;
    ``postprocess`` maps the inner response stream back out.  ``wrap`` closes
    the stage over an inner engine, yielding a composed engine — the Python
    rendering of the reference's forward/backward operator edges.
    """

    @abstractmethod
    async def preprocess(self, request: Context[Req]) -> Context[Any]:
        ...

    @abstractmethod
    async def postprocess(
        self, stream: ResponseStream[Any], request: Context[Req]
    ) -> ResponseStream[Resp]:
        ...

    def wrap(self, inner: AsyncEngine) -> "PipelineEngine[Req, Resp]":
        return PipelineEngine(self, inner)

    # Fluent alias matching the reference's ``.link()`` graph composition.
    def link(self, inner: AsyncEngine) -> "PipelineEngine[Req, Resp]":
        return self.wrap(inner)


class PipelineEngine(Generic[Req, Resp]):
    """An Operator closed over an inner engine."""

    def __init__(self, operator: Operator[Req, Resp], inner: AsyncEngine):
        self.operator = operator
        self.inner = inner

    async def generate(self, request: Context[Req]) -> ResponseStream[Resp]:
        inner_request = await self.operator.preprocess(request)
        inner_stream = await self.inner.generate(inner_request)
        return await self.operator.postprocess(inner_stream, request)
