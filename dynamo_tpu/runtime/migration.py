"""Live session migration: zero-loss mid-decode handoff between workers.

A decode session pinned to one worker is a liability the moment that worker
becomes hot, drains, or sits on the wrong side of a link — but killing and
re-running it burns the decoded prefix and the client's patience.  This
module moves the session instead: the :class:`MigrationCoordinator` (one per
``PushRouter``) snapshots the request's :class:`GenerationJournal` at a
stream window boundary, pre-admits the session on the destination in
``resume_from`` continuation mode (the continuation rides the prefix cache;
fleets with a KV transfer plane can attach a ``kv_streamer`` hook that ships
the blocks layer-wise over the multi-part ``kv_transfer`` protocol first),
then asks the consumer loop to *flip* the live stream — atomically, between
two items — onto the destination with a replay-dedupe cursor so every token
is delivered exactly once.

The safety invariant: **the source keeps decoding until the flip commits.**
Nothing in the handoff stops, kills, or even slows the source stream; every
failure before the commit point (destination dead, KV stream failed,
pre-admit timeout, flip never reached inside ``DYN_MIGRATE_FLIP_TIMEOUT_S``)
aborts by simply discarding the destination — the client never notices.
Migration is therefore never less safe than not migrating.

State machine (counted in ``dyn_migration_*``):

    validate ──► snapshot ──► [kv stream] ──► pre-admit ──► flip ──► release
       │failed      │aborted       │aborted       │aborted    │aborted
       ▼            ▼              ▼              ▼           ▼
     (refused — no handoff started; the session never left the source)

Exactly-once arithmetic: the journal snapshot ships ``payload_accepted``
tokens to the destination, and the source decodes ``delta`` more tokens
between the snapshot and the flip commit (``delta = total_recorded −
snap_total``, fold-invariant).  The destination regenerates that window, so
the flip wraps its stream in ``dedupe_stream(dst, skip=payload_accepted +
delta, ack_skip=delta)``: a continuation-mode engine (acks) re-emits only
the delta window; a replay-mode engine re-emits the whole prefix.  Either
way the cursor drops exactly the tokens the client has already seen.

Exposed three ways: ``dynctl migrate <request_id> <dst>`` (the well-known
``_dyn.ctl.migrate`` bus subject — only the dispatcher that owns the request
replies), graceful-drain integration (a deregistered worker's survivors are
migrated, not cancelled, when a destination exists), and the planner's
defragmentation loop (``dynamo_tpu/planner/defrag.py``).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Awaitable, Callable

from dynamo_tpu.observability import get_recorder
from dynamo_tpu.robustness import counters
from dynamo_tpu.robustness.faults import FAULTS, MIGRATE_FLIP, MIGRATE_HANDOFF
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.resume import GenerationJournal
from dynamo_tpu.utils import knobs
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("runtime.migration")

# Well-known control subject every dispatcher's coordinator subscribes to.
# A migrate request names a request_id; only the coordinator that OWNS that
# id replies, so one dynctl request finds the right dispatcher in a fleet
# of frontends without a directory.
MIGRATE_SUBJECT = "_dyn.ctl.migrate"

# Hop-class cost order for destination picking (mirrors topology/map.py):
# unknown hops price between ICI and DCN — informative maps should steer,
# not block, when a link simply has not been probed yet.
_HOP_COST = {"local": 0, "ici": 1, "": 2, "unknown": 2, "dcn": 3}


def _flight_event(status: str, **fields) -> None:
    """Mirror a migration state transition into every live flight recorder
    (best-effort — the handoff must never fail on observability)."""
    try:
        from dynamo_tpu.observability import flight

        for rec in flight.recorders():
            rec.record_event("migration", status=status, **fields)
    except Exception:  # noqa: BLE001
        pass


class _PendingFlip:
    """A prepared destination stream waiting for the consumer loop to swap
    it in at the next item boundary.  ``outcome`` transitions exactly once
    (``committed`` / ``aborted`` / ``finished`` / ``timeout``) — both the
    consumer's commit and the coordinator's timeout run on the same event
    loop and check-then-set without awaiting, so the transition is a plain
    race-free compare."""

    __slots__ = ("dst_raw", "dst_inst_id", "snap_total", "payload_accepted",
                 "done", "outcome")

    def __init__(self, dst_raw, dst_inst_id: int, snap_total: int,
                 payload_accepted: int):
        self.dst_raw = dst_raw
        self.dst_inst_id = dst_inst_id
        self.snap_total = snap_total
        self.payload_accepted = payload_accepted
        self.done = asyncio.Event()
        self.outcome: str | None = None


class MigrationHandle:
    """One live, journaled stream the coordinator may migrate.  Registered
    by the dispatch loop for the lifetime of ``_stream_with_retry`` and
    updated in place as the stream retries/resumes/flips across workers."""

    __slots__ = ("request_id", "journal", "ctx", "inst_id", "flip", "busy")

    def __init__(self, request_id: str, journal: GenerationJournal, ctx,
                 inst_id: int):
        self.request_id = request_id
        self.journal = journal
        self.ctx = ctx
        self.inst_id = inst_id          # worker currently decoding
        self.flip: _PendingFlip | None = None
        self.busy = False               # a migrate() is mid-handoff

    def flip_pending(self) -> bool:
        return self.flip is not None and self.flip.outcome is None

    def abort_flip(self, outcome: str = "aborted") -> None:
        """Resolve a pending flip without committing (stream errored,
        finished, or the dispatch loop is tearing down).  The coordinator's
        waiter owns killing the discarded destination stream."""
        flip, self.flip = self.flip, None
        if flip is not None and flip.outcome is None:
            flip.outcome = outcome
            flip.done.set()


class MigrationCoordinator:
    """Owns the migrate state machine for one PushRouter's live sessions."""

    def __init__(self, router):
        self.router = router
        self._handles: dict[str, MigrationHandle] = {}
        # optional best-effort KV pre-stream: async (handle, src, dst, hop)
        # -> None; raising aborts the migration before pre-admission.  Set
        # by deployments whose engines expose KV block export (the transfer
        # itself rides parallel/kv_transfer's layer-wise multi-part frames);
        # continuation-mode pre-admission alone rides the prefix cache.
        self.kv_streamer: Callable[..., Awaitable[None]] | None = None
        self._topology: Any = None      # TopologyMap | callable -> map | None
        self._ctl_sub = None
        self._ctl_task: asyncio.Task | None = None

    # -- session registry (called by the dispatch loop) --------------------
    def register(self, request_id: str, journal: GenerationJournal, ctx,
                 inst_id: int) -> MigrationHandle:
        handle = MigrationHandle(request_id, journal, ctx, inst_id)
        self._handles[request_id] = handle
        return handle

    def unregister(self, handle: MigrationHandle) -> None:
        handle.abort_flip()
        if self._handles.get(handle.request_id) is handle:
            self._handles.pop(handle.request_id, None)

    def resolve(self, request_id: str) -> MigrationHandle | None:
        """Find a live session by id.  The dispatch loop registers handles
        under the internal context id, but operators know the *request/trace*
        id (the ``x-request-id`` header, echoed in logs and response
        headers) — accept either: exact session id first, unique trace-id
        match second."""
        handle = self._handles.get(request_id)
        if handle is not None:
            return handle
        matches = [
            h for h in self._handles.values()
            if getattr(getattr(h.ctx, "trace", None), "trace_id", None)
            == request_id
        ]
        return matches[0] if len(matches) == 1 else None

    def sessions_on(self, inst_id: int) -> list[str]:
        return [
            rid for rid, h in self._handles.items()
            if h.inst_id == inst_id and not h.journal.finished
        ]

    def sessions(self) -> dict[str, int]:
        """request_id -> current worker, for the planner's defrag view."""
        return {
            rid: h.inst_id for rid, h in self._handles.items()
            if not h.journal.finished
        }

    # -- topology pricing --------------------------------------------------
    def attach_topology(self, topology) -> None:
        """Accepts a TopologyMap or a zero-arg callable returning one (the
        discovery layer's watcher refreshes its map in place)."""
        self._topology = topology

    def _topo_map(self):
        topo = self._topology() if callable(self._topology) else self._topology
        if topo is None or not topo.informative():
            return None  # uninformative map: no pricing signal, don't block
        return topo

    def hop(self, src: int, dst: int) -> str:
        topo = self._topo_map()
        return topo.hop(src, dst) if topo is not None else ""

    def pick_destination(self, src: int, *, allow_dcn: bool = False) -> int | None:
        """Cheapest-hop healthy destination: local/ICI neighbors first,
        unprobed links next, DCN only when the caller priced it in
        (drain/defrag of a doomed worker beats losing the session)."""
        candidates = [
            w for w in self.router.healthy_ids({src}) if w != src
        ]
        topo = self._topo_map()
        if topo is not None:
            priced = [
                (w, _HOP_COST.get(topo.hop(src, w), 2)) for w in candidates
            ]
            if not allow_dcn:
                priced = [(w, c) for w, c in priced if c < _HOP_COST["dcn"]]
            candidates = [w for w, _ in sorted(priced, key=lambda p: (p[1], p[0]))]
        if not candidates:
            return None
        return candidates[0]

    # -- the handoff -------------------------------------------------------
    async def migrate(
        self, request_id: str, dst: int | None = None, *,
        reason: str = "manual",
    ) -> dict:
        """Move one live session to ``dst`` (or the cheapest-hop healthy
        worker).  Returns a result dict either way; the session is NEVER
        worse off for having tried."""
        handle = self.resolve(request_id)
        allow_dcn = reason not in ("", "manual")

        def _refuse(error: str) -> dict:
            counters.incr("dyn_migration_failed_total")
            logger.warning("migrate %s refused: %s", request_id, error)
            return {"op": "migrate", "ok": False, "request_id": request_id,
                    "error": error}

        if handle is None or handle.journal.finished:
            return _refuse("unknown or finished session")
        if handle.busy:
            return _refuse("a migration is already in flight for this session")
        src = handle.inst_id
        if dst is None:
            dst = self.pick_destination(src, allow_dcn=allow_dcn)
            if dst is None:
                return _refuse("no eligible destination")
        if dst == src:
            return _refuse("destination is the worker already decoding it")
        if dst not in set(self.router.client.instance_ids):
            return _refuse(f"destination {dst:x} is not a registered instance")
        hop = self.hop(src, dst)
        if hop == "dcn" and not allow_dcn:
            return _refuse(
                "destination is a DCN hop away; cross-slice migration needs "
                "an explicit reason (drain/defrag/--reason)"
            )

        handle.busy = True
        t0 = time.monotonic()
        counters.incr("dyn_migration_started_total")
        _flight_event("started", request=request_id, src=f"{src:x}", dst=f"{dst:x}",
                      reason=reason)
        span = get_recorder().start(
            "migrate", getattr(handle.ctx, "trace", None), component="frontend",
            attrs={"request": request_id, "src": f"{src:x}", "dst": f"{dst:x}",
                   "hop": hop or "?", "reason": reason},
        )
        dst_raw = None
        try:
            # chaos seam: everything up to (and including) pre-admission
            FAULTS.check(MIGRATE_HANDOFF, request=request_id, dst=f"{dst:x}")
            # snapshot at a window boundary: the journal only mutates between
            # consumer yields on this same loop, so reading it here (no await
            # since the consumer last ran) IS the boundary
            snap_total = handle.journal.total_recorded
            resume_wire = handle.journal.resume_request()
            payload_accepted = len(resume_wire["resume_from"]["accepted"])
            if self.kv_streamer is not None:
                await self.kv_streamer(handle, src, dst, hop)
            # pre-admit: pinned rendezvous on the destination; the engine
            # starts regenerating from the snapshot immediately — all of it
            # overlapped with the still-decoding source
            resumed = Context(resume_wire, handle.ctx)
            dst_raw, dst_id = await self.router._rendezvous(resumed, dst, set())
            # chaos seam: the flip itself
            FAULTS.check(MIGRATE_FLIP, request=request_id, dst=f"{dst:x}")
            if (handle.journal.finished
                    or self._handles.get(handle.request_id) is not handle):
                raise RuntimeError("session finished during the handoff")
            if handle.flip_pending():
                raise RuntimeError("another flip is already pending")
            flip = _PendingFlip(dst_raw, dst_id, snap_total, payload_accepted)
            handle.flip = flip
            try:
                await asyncio.wait_for(
                    flip.done.wait(), knobs.get("DYN_MIGRATE_FLIP_TIMEOUT_S")
                )
            except asyncio.TimeoutError:
                pass
            # the consumer commits synchronously between items; whatever
            # state we observe here is final for this flip
            if flip.outcome is None:
                flip.outcome = "timeout"
                if handle.flip is flip:
                    handle.flip = None
            if flip.outcome != "committed":
                raise RuntimeError(f"flip did not commit ({flip.outcome})")
        except BaseException as exc:
            if dst_raw is not None:
                # discard the pre-admitted destination stream: kills the
                # worker-side context for that hop only (data-plane control
                # frame), the client-visible source stream is untouched
                await dst_raw.send_control("kill")
            counters.incr("dyn_migration_aborted_total")
            _flight_event("aborted", request=request_id, error=repr(exc))
            if span is not None:
                span.end(status="error", error=repr(exc))
            logger.warning(
                "migrate %s %x->%x aborted (%s); session continues on source",
                request_id, src, dst, exc,
            )
            if isinstance(exc, asyncio.CancelledError):
                raise
            return {"op": "migrate", "ok": False, "aborted": True,
                    "request_id": request_id, "src": f"{src:x}",
                    "dst": f"{dst:x}", "error": repr(exc)}
        finally:
            handle.busy = False
        hidden = time.monotonic() - t0
        counters.incr("dyn_migration_committed_total")
        _flight_event("committed", request=request_id, hidden_s=round(hidden, 4))
        counters.incr("dyn_migration_hidden_seconds", hidden)
        if span is not None:
            span.end(hidden_s=round(hidden, 4))
        logger.info(
            "migrated %s %x->%x (%s, reason=%s) in %.3fs hidden",
            request_id, src, dst_id, hop or "unpriced", reason, hidden,
        )
        return {"op": "migrate", "ok": True, "request_id": request_id,
                "src": f"{src:x}", "dst": f"{dst_id:x}", "hop": hop,
                "reason": reason, "hidden_s": round(hidden, 4)}

    async def migrate_off(self, inst_id: int, *, reason: str = "drain") -> list[dict]:
        """Drain integration: move every live session off ``inst_id``.
        Each migration picks its own destination; failures degrade to the
        existing cancel-via-resume drain path, so this is strictly a
        latency win, never a safety risk."""
        results = []
        for rid in self.sessions_on(inst_id):
            results.append(await self.migrate(rid, None, reason=reason))
        return results

    # -- drain hook --------------------------------------------------------
    def attach_client(self, client) -> None:
        """Subscribe to instance-removal events so a draining worker's
        survivors are migrated during its natural-completion window (the
        drain deletes its instance key in phase 1, cancels in phase 2)."""
        client.on_instance_removed.append(self._on_instance_removed)

    def _on_instance_removed(self, inst_id: int) -> None:
        if self.sessions_on(inst_id):
            spawn_logged(self.migrate_off(inst_id, reason="drain"))

    # -- control-plane verb ------------------------------------------------
    async def serve_ctl(self, bus) -> None:
        if self._ctl_sub is not None:
            return
        self._ctl_sub = await bus.subscribe(MIGRATE_SUBJECT)
        self._ctl_task = spawn_logged(self._ctl_loop(bus))

    async def stop(self) -> None:
        sub, self._ctl_sub = self._ctl_sub, None
        if sub is not None:
            await sub.unsubscribe()
        task, self._ctl_task = self._ctl_task, None
        if task is not None and task is not asyncio.current_task():
            task.cancel()

    def _resolve_instance(self, needle: str) -> int | None:
        """Hex-prefix instance resolution (same UX as ``dynctl drain``);
        None on no/ambiguous match."""
        needle = needle.lower()
        if needle.startswith("0x"):
            needle = needle[2:]
        matches = []
        for iid in self.router.client.instance_ids:
            hex16 = f"{iid:016x}"
            if needle in (hex16, f"{iid:x}") or hex16.startswith(needle):
                matches.append(iid)
        return matches[0] if len(matches) == 1 else None

    async def _ctl_loop(self, bus) -> None:
        assert self._ctl_sub is not None
        async for msg in self._ctl_sub:
            try:
                op = json.loads(msg.payload.decode())
            except Exception:  # noqa: BLE001
                continue
            if op.get("op") != "migrate":
                continue
            rid = str(op.get("request_id") or "")
            if self.resolve(rid) is None:
                # a fleet runs many dispatchers on this subject; only the
                # owner answers, so an unknown id times out at the caller
                continue
            dst_arg = op.get("dst")
            dst: int | None = None
            result: dict | None = None
            if dst_arg:
                dst = self._resolve_instance(str(dst_arg))
                if dst is None:
                    counters.incr("dyn_migration_failed_total")
                    result = {"op": "migrate", "ok": False, "request_id": rid,
                              "error": f"no unique instance matches {dst_arg!r}"}
            if result is None:
                result = await self.migrate(
                    rid, dst, reason=str(op.get("reason") or "manual")
                )
            if msg.reply_to:
                await bus.publish(msg.reply_to, json.dumps(result).encode())
