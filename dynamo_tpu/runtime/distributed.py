"""DistributedRuntime — the node-global handle.

Bundles the control-plane connection (KV + bus), the lazy TCP response-stream
server, lease keep-alives and the supervised task group (reference:
lib/runtime/src/lib.rs:77-100, src/distributed.rs:34-86).
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.runtime.component import Namespace
from dynamo_tpu.runtime.controlplane import connect_control_plane
from dynamo_tpu.runtime.controlplane.interface import ControlPlane, Lease
from dynamo_tpu.runtime.dataplane import ResponseStreamServer
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger
from dynamo_tpu.utils.tasks import CriticalTaskGroup
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("runtime.distributed")


class DistributedRuntime:
    """One per process.  ``await DistributedRuntime.create()``."""

    def __init__(self, config: RuntimeConfig, plane: ControlPlane):
        self.config = config
        self.plane = plane
        self.tasks = CriticalTaskGroup(on_failure=self._on_critical_failure)
        self._data_server: ResponseStreamServer | None = None
        self._data_server_lock = asyncio.Lock()
        self._keepalive_loops: dict[int, asyncio.Task] = {}
        self._shutdown_event = asyncio.Event()

    @classmethod
    async def create(cls, config: RuntimeConfig | None = None, **overrides) -> "DistributedRuntime":
        configure_logging()
        config = config or RuntimeConfig.from_env(**overrides)
        plane = await connect_control_plane(config.control_plane)
        return cls(config, plane)

    # -- components --------------------------------------------------------
    def namespace(self, name: str | None = None) -> Namespace:
        return Namespace(self, name or self.config.namespace)

    # -- data plane --------------------------------------------------------
    async def data_server(self) -> ResponseStreamServer:
        """Lazily started TCP response-stream server (reference: lazy TCP
        server in DistributedRuntime)."""
        async with self._data_server_lock:
            if self._data_server is None:
                self._data_server = ResponseStreamServer(
                    self.config.data_host, self.config.data_port
                )
                await self._data_server.start()
            return self._data_server

    # -- leases ------------------------------------------------------------
    def register_keepalive(self, lease: Lease) -> None:
        """Keep a lease alive until revoked (memory backend has no client-side
        keep-alive loop; remote backend already self-heartbeats)."""
        if hasattr(self.plane.kv, "_keepalive_tasks"):
            return  # RemoteKV heartbeats on grant

        async def loop() -> None:
            while not lease.revoked:
                await asyncio.sleep(max(lease.ttl / 3.0, 0.05))
                await self.plane.kv.keep_alive(lease)

        self._keepalive_loops[lease.id] = spawn_logged(loop())

    # -- lifecycle ---------------------------------------------------------
    def _on_critical_failure(self, exc: BaseException) -> None:
        logger.error("critical task failure, shutting down runtime: %r", exc)
        self._shutdown_event.set()

    def shutdown(self) -> None:
        self._shutdown_event.set()

    async def wait_for_shutdown(self) -> None:
        await self._shutdown_event.wait()

    async def close(self) -> None:
        self._shutdown_event.set()
        for task in self._keepalive_loops.values():
            task.cancel()
        await self.tasks.cancel_all()
        if self._data_server is not None:
            await self._data_server.stop()
            self._data_server = None
        await self.plane.close()
