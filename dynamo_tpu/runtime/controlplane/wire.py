"""Wire protocol for the dynctl control-plane RPC: 4-byte length-prefixed
msgpack frames over TCP.

Frame shapes:
- request:  ``{"i": id, "m": method, "a": [args...]}``
- response: ``{"i": id, "ok": bool, "r": result}`` / ``{"i": id, "ok": False, "e": msg}``
- push:     ``{"s": stream_id, "t": kind, "d": data}`` (watch/subscription events)
"""

from __future__ import annotations

import asyncio
import struct

import msgpack

MAX_FRAME = 512 * 1024 * 1024  # 512 MiB (object store chunks stay well below)

_LEN = struct.Struct("!I")


def pack_frame(obj: dict) -> bytes:
    payload = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(payload)) + payload


async def read_frame(reader: asyncio.StreamReader) -> dict | None:
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    try:
        payload = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(payload, raw=False)


# RPC frames carry a TraceContext under the shared reserved key: request-
# scoped RPCs (the push router's envelope publish, via
# ``RpcConnection.call(..., trace=...)``) stamp it so the dynctl server can
# attribute failures to the request trace (``frame_trace`` server-side).
# Canonical stamp/decode pair lives in observability.trace.
from dynamo_tpu.observability.trace import (  # noqa: E402 (re-export)
    read_trace as frame_trace,
    stamp_trace as with_trace,
)


def kv_entry_to_wire(entry) -> dict:
    return {"k": entry.key, "v": entry.value, "rev": entry.revision, "lease": entry.lease_id}


def kv_entry_from_wire(d: dict):
    from dynamo_tpu.runtime.controlplane.interface import KVEntry

    return KVEntry(key=d["k"], value=d["v"], revision=d["rev"], lease_id=d["lease"])
