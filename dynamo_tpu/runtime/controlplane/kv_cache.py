"""Write-through watch cache over a control-plane KV prefix.

Local reads served from memory, kept fresh by a prefix watch; writes go
through to the store and update the local view optimistically (reference:
``EtcdKvCache`` — lib/runtime/src/transports/etcd.rs:474-599 — used for
hot-reloaded runtime config such as the disagg router threshold).

Usage:
    cache = await KvWatchCache.create(plane.kv, "config/router/")
    value = cache.get("threshold")          # no network IO
    await cache.put("threshold", b"512")    # write-through
    await cache.close()
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.runtime.controlplane.interface import (
    KeyValueStore,
    Watch,
    WatchEventType,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("runtime.controlplane.kv_cache")


class KvWatchCache:
    """A prefix-scoped KV view: snapshot-primed, watch-maintained,
    write-through."""

    def __init__(self, kv: KeyValueStore, prefix: str):
        self.kv = kv
        self.prefix = prefix
        self._data: dict[str, bytes] = {}
        self._watch: Watch | None = None
        self._task: asyncio.Task | None = None
        self._changed = asyncio.Event()
        self._stale = False
        self._closing = False

    @classmethod
    async def create(cls, kv: KeyValueStore, prefix: str) -> "KvWatchCache":
        cache = cls(kv, prefix)
        cache._watch = kv.watch_prefix(prefix)
        cache._task = asyncio.ensure_future(cache._pump())
        # the watch's initial snapshot (applied by the pump) IS the prime —
        # ready() resolves once the view is complete
        await cache._watch.ready()
        return cache

    async def _pump(self) -> None:
        assert self._watch is not None
        try:
            # against a self-healing remote plane this loop survives
            # connection loss transparently: the watch resyncs (snapshot
            # PUTs + synthetic DELETEs) and the view converges — `stale`
            # only trips on a TERMINAL watch death (reconnect disabled,
            # plane closed, or a memory-backend watch cancelled externally)
            async for event in self._watch:
                key = event.entry.key
                if not key.startswith(self.prefix):
                    continue
                short = key[len(self.prefix):]
                if event.type == WatchEventType.PUT:
                    self._data[short] = event.entry.value
                else:
                    self._data.pop(short, None)
                self._changed.set()
                self._changed = asyncio.Event()
        except ConnectionError:
            pass  # handled below: the finally marks the view stale
        finally:
            # watch ended for good: the view stops updating — flag it and
            # wake any waiters so callers never block forever on a dead cache
            if not self._closing:
                self._stale = True
                logger.warning(
                    "watch for prefix %r ended; cached view is stale", self.prefix
                )
            self._changed.set()

    @property
    def stale(self) -> bool:
        """True once the backing watch has died (view no longer updates)."""
        return self._stale

    # -- local reads -------------------------------------------------------
    def get(self, key: str, default: bytes | None = None) -> bytes | None:
        return self._data.get(key, default)

    def keys(self) -> list[str]:
        return sorted(self._data)

    def items(self) -> dict[str, bytes]:
        return dict(self._data)

    def __len__(self) -> int:
        return len(self._data)

    async def wait_changed(self, timeout: float | None = None) -> bool:
        """Block until the view changes (True) or timeout (False)."""
        changed = self._changed
        try:
            await asyncio.wait_for(changed.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    # -- write-through -----------------------------------------------------
    async def put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self.kv.put(self.prefix + key, value, lease_id)
        self._data[key] = value  # optimistic; the watch confirms

    async def delete(self, key: str) -> None:
        await self.kv.delete(self.prefix + key)
        self._data.pop(key, None)

    async def close(self) -> None:
        self._closing = True
        if self._watch is not None:
            self._watch.cancel()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
