"""In-process control plane (static/dev mode and tests).

Implements full etcd/NATS-class semantics — revisions, CAS, leases with expiry
reaping, prefix watches, queue groups, request/reply, durable queues, object
store — entirely in process.  The ``dynctl`` TCP server wraps this same state
machine; memory mode is the reference's "static mode without discovery"
(reference: lib/runtime/src/distributed.rs:86) but with discovery working.
"""

from __future__ import annotations

import asyncio
import itertools
import time
import uuid
from collections import defaultdict

from dynamo_tpu.runtime.controlplane.interface import (
    ControlPlane,
    KVEntry,
    KeyValueStore,
    Lease,
    Message,
    MessageBus,
    Subscription,
    Watch,
    WatchEvent,
    WatchEventType,
    subject_matches,
)
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("runtime.controlplane.memory")


class MemoryKV(KeyValueStore):
    def __init__(self) -> None:
        self._data: dict[str, KVEntry] = {}
        self._revision = 0
        self._leases: dict[int, tuple[Lease, float]] = {}  # id -> (lease, deadline)
        self._lease_keys: dict[int, set[str]] = defaultdict(set)
        self._watches: list[tuple[str, Watch]] = []
        self._lease_counter = itertools.count(1)
        self._reaper: asyncio.Task | None = None

    # -- events ------------------------------------------------------------
    def _notify(self, event: WatchEvent) -> None:
        live = []
        for prefix, watch in self._watches:
            if watch._cancelled:
                continue  # prune dead registrations as we go
            if event.entry.key.startswith(prefix):
                watch._emit(event)
            live.append((prefix, watch))
        self._watches = live

    def _ensure_reaper(self) -> None:
        if self._reaper is None or self._reaper.done():
            self._reaper = spawn_logged(self._reap_loop())

    async def _reap_loop(self) -> None:
        while self._leases:
            await asyncio.sleep(0.2)
            now = time.monotonic()
            expired = [lid for lid, (_, deadline) in self._leases.items() if deadline < now]
            for lid in expired:
                await self._expire_lease(lid)
        self._reaper = None

    async def _expire_lease(self, lease_id: int) -> None:
        entry = self._leases.pop(lease_id, None)
        if entry is None:
            return
        lease, _ = entry
        lease._revoked.set()
        for key in self._lease_keys.pop(lease_id, set()):
            old = self._data.get(key)
            # only reap keys this lease still owns: a reconnect re-grant
            # re-puts the key under its NEW lease id, and the old lease
            # expiring afterwards must not take the live key with it
            if old is not None and old.lease_id == lease_id:
                del self._data[key]
                self._notify(WatchEvent(WatchEventType.DELETE, old))

    # -- KeyValueStore -----------------------------------------------------
    async def put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        self._revision += 1
        prev = self._data.get(key)
        if prev is not None and prev.lease_id and prev.lease_id != lease_id:
            # re-put under a different (or no) lease transfers ownership;
            # leaving the key in the old lease's set would let that lease's
            # expiry delete a key it no longer owns
            self._lease_keys[prev.lease_id].discard(key)
        entry = KVEntry(key=key, value=value, revision=self._revision, lease_id=lease_id)
        self._data[key] = entry
        if lease_id:
            self._lease_keys[lease_id].add(key)
        self._notify(WatchEvent(WatchEventType.PUT, entry))
        return self._revision

    async def create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        if key in self._data:
            return False
        await self.put(key, value, lease_id)
        return True

    async def get(self, key: str) -> KVEntry | None:
        return self._data.get(key)

    async def get_prefix(self, prefix: str) -> list[KVEntry]:
        return [e for k, e in sorted(self._data.items()) if k.startswith(prefix)]

    async def delete(self, key: str) -> bool:
        old = self._data.pop(key, None)
        if old is None:
            return False
        if old.lease_id:
            self._lease_keys[old.lease_id].discard(key)
        self._notify(WatchEvent(WatchEventType.DELETE, old))
        return True

    async def delete_prefix(self, prefix: str) -> int:
        keys = [k for k in self._data if k.startswith(prefix)]
        for k in keys:
            await self.delete(k)
        return len(keys)

    async def grant_lease(self, ttl: float) -> Lease:
        lease = Lease(id=next(self._lease_counter), ttl=ttl)
        self._leases[lease.id] = (lease, time.monotonic() + ttl)
        self._ensure_reaper()
        return lease

    async def keep_alive(self, lease: Lease) -> None:
        if lease.id in self._leases:
            self._leases[lease.id] = (lease, time.monotonic() + lease.ttl)

    async def revoke_lease(self, lease: Lease) -> None:
        await self._expire_lease(lease.id)

    def watch_prefix(self, prefix: str) -> Watch:
        watch = Watch()
        for entry in list(self._data.values()):
            if entry.key.startswith(prefix):
                watch._emit(WatchEvent(WatchEventType.PUT, entry))
        watch._emit_sync()  # snapshot boundary
        self._watches.append((prefix, watch))
        return watch


class MemoryBus(MessageBus):
    def __init__(self) -> None:
        # subject pattern -> {queue_group_or_None -> [subscriptions]}
        self._subs: list[tuple[str, str | None, Subscription]] = []
        self._rr: dict[tuple[str, str], int] = defaultdict(int)
        # work-queue items: (payload, enqueue instant on this bus's clock)
        self._queues: dict[str, asyncio.Queue[tuple[bytes, float]]] = defaultdict(
            asyncio.Queue
        )
        self._objects: dict[str, dict[str, bytes]] = defaultdict(dict)

    async def publish(
        self, subject: str, payload: bytes, reply_to: str | None = None, trace=None
    ) -> int:
        # trace: accepted for interface parity; in-process delivery needs no
        # frame-level correlation (the request envelope already carries it)
        msg = Message(subject=subject, payload=payload, reply_to=reply_to)
        delivered = 0
        # group -> matching members; None-group members all get a copy
        grouped: dict[str, list[Subscription]] = defaultdict(list)
        for pattern, group, sub in list(self._subs):
            if sub._closed or not subject_matches(pattern, subject):
                continue
            if group is None:
                sub._deliver(msg)
                delivered += 1
            else:
                grouped[f"{pattern}|{group}"].append(sub)
        for key, members in grouped.items():
            idx = self._rr[(key, "")] % len(members)
            self._rr[(key, "")] += 1
            members[idx]._deliver(msg)
            delivered += 1
        return delivered

    async def subscribe(self, subject: str, queue_group: str | None = None) -> Subscription:
        sub = Subscription(subject)
        self._subs.append((subject, queue_group, sub))
        return sub

    async def request(self, subject: str, payload: bytes, timeout: float = 5.0) -> bytes:
        inbox = f"_inbox.{uuid.uuid4().hex}"
        sub = await self.subscribe(inbox)
        try:
            await self.publish(subject, payload, reply_to=inbox)
            msg = await asyncio.wait_for(sub.__anext__(), timeout)
            return msg.payload
        finally:
            await sub.unsubscribe()

    async def queue_publish(self, queue: str, payload: bytes) -> None:
        # items carry their enqueue instant (this bus's monotonic clock) so
        # queue_pop_meta can report broker-measured age: when this bus lives
        # in a dynctl server, publish and pop both happen here, making the
        # age immune to producer/consumer wall-clock skew
        self._queues[queue].put_nowait((payload, time.monotonic()))

    async def queue_pop(self, queue: str, timeout: float | None = None) -> bytes | None:
        item = await self.queue_pop_meta(queue, timeout)
        return None if item is None else item[0]

    async def queue_pop_meta(
        self, queue: str, timeout: float | None = None
    ) -> tuple[bytes, float | None] | None:
        q = self._queues[queue]
        try:
            if timeout is None:
                payload, enq = await q.get()
            else:
                payload, enq = await asyncio.wait_for(q.get(), timeout)
        except asyncio.TimeoutError:
            return None
        return payload, time.monotonic() - enq

    async def queue_len(self, queue: str) -> int:
        return self._queues[queue].qsize()

    async def object_put(self, bucket: str, name: str, data: bytes) -> None:
        self._objects[bucket][name] = data

    async def object_get(self, bucket: str, name: str) -> bytes | None:
        return self._objects[bucket].get(name)

    async def object_delete(self, bucket: str, name: str) -> bool:
        return self._objects[bucket].pop(name, None) is not None


class MemoryControlPlane(ControlPlane):
    """A fully in-process control plane instance."""

    _named: dict[str, "MemoryControlPlane"] = {}

    def __init__(self) -> None:
        self.kv: MemoryKV = MemoryKV()
        self.bus: MemoryBus = MemoryBus()

    @classmethod
    def named(cls, name: str) -> "MemoryControlPlane":
        """Process-wide shared instance (so runtimes in one process discover
        each other, like pointing at the same etcd)."""
        if name not in cls._named:
            cls._named[name] = cls()
        return cls._named[name]

    @classmethod
    def reset_named(cls) -> None:
        cls._named.clear()

    async def close(self) -> None:
        pass
