"""Abstract control-plane interfaces (etcd-class KV + NATS-class bus)."""

from __future__ import annotations

import asyncio
import enum
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import AsyncIterator


# --------------------------------------------------------------------------
# Key-value store (discovery, leases, config watch)
# --------------------------------------------------------------------------


@dataclass
class KVEntry:
    key: str
    value: bytes
    revision: int = 0
    lease_id: int = 0


class WatchEventType(enum.Enum):
    PUT = "put"
    DELETE = "delete"


@dataclass
class WatchEvent:
    type: WatchEventType
    entry: KVEntry


@dataclass
class Lease:
    """A liveness lease; keys attached to it vanish when it expires.

    (Reference: etcd leases, lib/runtime/src/transports/etcd.rs:51-88 — the
    liveness primitive for failure detection.)
    """

    id: int
    ttl: float
    _revoked: asyncio.Event = field(default_factory=asyncio.Event)

    @property
    def revoked(self) -> bool:
        return self._revoked.is_set()


class KeyValueStore(ABC):
    @abstractmethod
    async def put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        """Put; returns new revision."""

    @abstractmethod
    async def create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        """Atomically create iff absent (etcd CAS kv_create). False if exists."""

    @abstractmethod
    async def get(self, key: str) -> KVEntry | None:
        ...

    @abstractmethod
    async def get_prefix(self, prefix: str) -> list[KVEntry]:
        ...

    @abstractmethod
    async def delete(self, key: str) -> bool:
        ...

    @abstractmethod
    async def delete_prefix(self, prefix: str) -> int:
        ...

    @abstractmethod
    async def grant_lease(self, ttl: float) -> Lease:
        """Grant a lease; caller must keep it alive via ``keep_alive``."""

    @abstractmethod
    async def keep_alive(self, lease: Lease) -> None:
        """Refresh lease TTL once."""

    @abstractmethod
    async def revoke_lease(self, lease: Lease) -> None:
        ...

    @abstractmethod
    def watch_prefix(self, prefix: str) -> "Watch":
        """Watch a prefix: yields initial snapshot as PUTs, then live events."""


# In-queue marker separating the initial snapshot from live events.  It is
# swallowed by ``Watch.__anext__`` (consumers never see it); dequeueing it
# sets the watch's ready event, so ``await watch.ready()`` means "the
# consumer has drained the full snapshot" — not merely "it was enqueued".
WATCH_SYNC = object()


class Watch:
    """Async stream of WatchEvents with a cancel handle and an
    end-of-snapshot ``ready()`` signal."""

    def __init__(self) -> None:
        self._queue: asyncio.Queue[object | None] = asyncio.Queue()
        self._cancelled = False
        self._ready = asyncio.Event()
        self._error: BaseException | None = None

    def _emit(self, event: WatchEvent) -> None:
        if not self._cancelled:
            self._queue.put_nowait(event)

    def _emit_sync(self) -> None:
        if not self._cancelled:
            self._queue.put_nowait(WATCH_SYNC)

    def _close(self) -> None:
        self._ready.set()  # never leave ready() waiters hanging
        self._queue.put_nowait(None)

    def _fail(self, exc: BaseException) -> None:
        """Mark the watch broken (e.g. the connection dropped during
        startup): ``ready()`` waiters and iterators re-raise instead of
        hanging forever.  Non-connection causes (rpc errors, timeouts) are
        normalized to ConnectionError so consumers handle one type."""
        if self._cancelled:
            return
        if not isinstance(exc, ConnectionError):
            exc = ConnectionError(f"watch failed: {exc!r}")
        self._error = exc
        self._ready.set()
        self._queue.put_nowait(None)

    def cancel(self) -> None:
        self._cancelled = True
        self._ready.set()
        self._queue.put_nowait(None)

    async def ready(self) -> None:
        """Resolves once the initial snapshot has been consumed from this
        watch (or the watch closed); raises if the watch failed to start."""
        await self._ready.wait()
        if self._error is not None:
            raise self._error

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        while True:
            event = await self._queue.get()
            if event is None or self._cancelled:
                self._ready.set()
                if self._error is not None and not self._cancelled:
                    raise self._error
                raise StopAsyncIteration
            if event is WATCH_SYNC:
                self._ready.set()
                continue
            return event  # type: ignore[return-value]


# --------------------------------------------------------------------------
# Message bus (request push, work queues, object store, stats)
# --------------------------------------------------------------------------


@dataclass
class Message:
    subject: str
    payload: bytes
    reply_to: str | None = None


class Subscription:
    """Async stream of Messages for a subject (optionally queue-grouped)."""

    def __init__(self, subject: str) -> None:
        self.subject = subject
        self._queue: asyncio.Queue[Message | None] = asyncio.Queue()
        self._closed = False

    def _deliver(self, msg: Message) -> None:
        if not self._closed:
            self._queue.put_nowait(msg)

    async def unsubscribe(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)

    def pending(self) -> int:
        return self._queue.qsize()

    def __aiter__(self) -> AsyncIterator[Message]:
        return self

    async def __anext__(self) -> Message:
        msg = await self._queue.get()
        if msg is None or self._closed:
            raise StopAsyncIteration
        return msg


@dataclass
class Bucket:
    """Object-store bucket handle (model artifacts; reference:
    lib/runtime/src/transports/nats.rs:123-211)."""

    name: str


class MessageBus(ABC):
    @abstractmethod
    async def publish(
        self, subject: str, payload: bytes, reply_to: str | None = None, trace=None
    ) -> int | None:
        """Returns the number of subscribers the message reached, or None
        when the backend cannot tell (e.g. an older dynctl server).  A hard
        0 lets publishers detect a dark subject — a worker mid-resubscribe
        after a control-plane reconnect, or dead — and re-publish instead
        of waiting out a rendezvous timeout on a message nobody received.

        ``trace``: optional TraceContext stamped on the transport frame
        by remote implementations (request-scoped publishes only); purely
        advisory — delivery semantics never depend on it."""
        ...

    @abstractmethod
    async def subscribe(self, subject: str, queue_group: str | None = None) -> Subscription:
        """Wildcard ``*`` (one token) and ``>`` (rest) are supported.

        Within a queue group, each message goes to exactly one subscriber.
        """

    @abstractmethod
    async def request(self, subject: str, payload: bytes, timeout: float = 5.0) -> bytes:
        """Request/reply (service stats scraping)."""

    # ---- durable work queue (JetStream work-queue analog; prefill queue) --
    @abstractmethod
    async def queue_publish(self, queue: str, payload: bytes) -> None:
        ...

    @abstractmethod
    async def queue_pop(self, queue: str, timeout: float | None = None) -> bytes | None:
        """Pop one item; None on timeout. Exactly-one-consumer semantics."""

    async def queue_pop_meta(
        self, queue: str, timeout: float | None = None
    ) -> tuple[bytes, float | None] | None:
        """Pop one item with its broker-measured age in seconds.

        The age is enqueue→pop elapsed ON THE BROKER'S OWN CLOCK (NATS
        JetStream exposes the same via server-side message timestamps), so
        consumers can bound item staleness without trusting cross-host
        wall-clock agreement.  Backends that don't track enqueue times
        return ``(payload, None)`` — this default just wraps queue_pop.
        """
        payload = await self.queue_pop(queue, timeout)
        return None if payload is None else (payload, None)

    @abstractmethod
    async def queue_len(self, queue: str) -> int:
        ...

    # ---- object store -----------------------------------------------------
    @abstractmethod
    async def object_put(self, bucket: str, name: str, data: bytes) -> None:
        ...

    @abstractmethod
    async def object_get(self, bucket: str, name: str) -> bytes | None:
        ...

    @abstractmethod
    async def object_delete(self, bucket: str, name: str) -> bool:
        ...


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style subject matching: ``a.*.c`` and ``a.>``."""
    p_tokens = pattern.split(".")
    s_tokens = subject.split(".")
    for i, tok in enumerate(p_tokens):
        if tok == ">":
            return True
        if i >= len(s_tokens):
            return False
        if tok != "*" and tok != s_tokens[i]:
            return False
    return len(p_tokens) == len(s_tokens)


class ControlPlane(ABC):
    """A connected control plane: KV store + message bus + lifecycle."""

    kv: KeyValueStore
    bus: MessageBus

    @abstractmethod
    async def close(self) -> None:
        ...
