"""Control plane: service discovery + messaging.

The reference delegates its control plane to external infra — etcd for
discovery/leases/config-watch and NATS for request push, work queues, object
store and service stats (reference: lib/runtime/src/transports/{etcd,nats}.rs).
dynamo_tpu ships its own native control plane with the same semantics:

- ``KeyValueStore`` — etcd-class: versioned KV, compare-and-create, prefix
  get/watch with initial snapshot, leases with TTL + keep-alive; lease expiry
  deletes attached keys and emits delete events to watchers.
- ``MessageBus``   — NATS-class: subjects, queue-group subscriptions,
  request/reply, durable work queues (JetStream-analog), object store.

Backends:
- ``memory://``    — in-process singletons (static/dev mode and tests).
- ``host:port``    — msgpack-RPC TCP client to a ``dynctl`` server process
  (the distributed mode; see ``dynamo_tpu.runtime.controlplane.server``).
  Self-healing by default: lost connections reconnect with backoff and
  resync leases/watches/subscriptions (docs/robustness.md).
"""

from dynamo_tpu.runtime.controlplane.interface import (
    Bucket,
    KVEntry,
    KeyValueStore,
    Lease,
    MessageBus,
    Message,
    Subscription,
    WatchEvent,
    WatchEventType,
)
from dynamo_tpu.runtime.controlplane.kv_cache import KvWatchCache
from dynamo_tpu.runtime.controlplane.memory import MemoryControlPlane
from dynamo_tpu.runtime.controlplane.connect import connect_control_plane

__all__ = [
    "Bucket",
    "KVEntry",
    "KeyValueStore",
    "KvWatchCache",
    "Lease",
    "Message",
    "MessageBus",
    "MemoryControlPlane",
    "Subscription",
    "WatchEvent",
    "WatchEventType",
    "connect_control_plane",
]
