"""dynctl — the standalone control-plane server.

One lightweight TCP process replacing the reference's external etcd + NATS
deployment (reference: deploy/metrics/docker-compose.yml spins up both).  It
hosts the same state machine as ``MemoryControlPlane`` behind a msgpack-RPC
protocol, so memory mode and distributed mode behave identically.

Run: ``python -m dynamo_tpu.cli.dynctl --port 2379``
"""

from __future__ import annotations

import asyncio
import itertools

from dynamo_tpu.runtime.controlplane.interface import WATCH_SYNC, Subscription, Watch
from dynamo_tpu.runtime.controlplane.memory import MemoryControlPlane
from dynamo_tpu.runtime.controlplane.wire import (
    frame_trace,
    kv_entry_to_wire,
    pack_frame,
    read_frame,
)
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("runtime.controlplane.server")


class ControlPlaneServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 2379):
        self.host = host
        self.port = port
        self.state = MemoryControlPlane()
        self._server: asyncio.Server | None = None
        self._stream_ids = itertools.count(1)
        self._client_writers: set[asyncio.StreamWriter] = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]
        logger.info("dynctl listening on %s:%d", self.host, self.port)

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # drop established client connections too: stop() must look like a
        # dead server to clients (their reconnect logic depends on seeing
        # EOF), not like a server that merely stopped accepting
        for writer in list(self._client_writers):
            writer.close()
        self._client_writers.clear()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._client_writers.add(writer)
        # per-connection resources torn down on disconnect
        watches: dict[int, Watch] = {}
        subs: dict[int, Subscription] = {}
        pumps: list[asyncio.Task] = []
        write_lock = asyncio.Lock()

        async def send(obj: dict) -> None:
            async with write_lock:
                writer.write(pack_frame(obj))
                await writer.drain()

        async def pump_watch(stream_id: int, watch: Watch) -> None:
            # Reads the raw queue (not __anext__, which swallows the sync
            # sentinel) so the end-of-snapshot boundary is forwarded on the
            # wire and the remote watch's ready() has true snapshot semantics.
            while True:
                item = await watch._queue.get()
                if item is None or watch._cancelled:
                    break
                if item is WATCH_SYNC:
                    await send({"s": stream_id, "t": "sync", "d": None})
                    continue
                await send(
                    {"s": stream_id, "t": "kv", "d": {"type": item.type.value, "entry": kv_entry_to_wire(item.entry)}}
                )
            await send({"s": stream_id, "t": "close", "d": None})

        async def pump_sub(stream_id: int, sub: Subscription) -> None:
            async for msg in sub:
                await send(
                    {"s": stream_id, "t": "bus", "d": {"subject": msg.subject, "payload": msg.payload, "reply_to": msg.reply_to}}
                )
            await send({"s": stream_id, "t": "close", "d": None})

        async def dispatch(method: str, args: list):
            kv, bus = self.state.kv, self.state.bus
            if method == "kv.put":
                return await kv.put(args[0], args[1], args[2])
            if method == "kv.create":
                return await kv.create(args[0], args[1], args[2])
            if method == "kv.get":
                entry = await kv.get(args[0])
                return kv_entry_to_wire(entry) if entry else None
            if method == "kv.get_prefix":
                return [kv_entry_to_wire(e) for e in await kv.get_prefix(args[0])]
            if method == "kv.delete":
                return await kv.delete(args[0])
            if method == "kv.delete_prefix":
                return await kv.delete_prefix(args[0])
            if method == "kv.grant_lease":
                lease = await kv.grant_lease(args[0])
                return lease.id
            if method == "kv.keep_alive":
                lease_entry = kv._leases.get(args[0])
                if lease_entry is None:
                    return False
                await kv.keep_alive(lease_entry[0])
                return True
            if method == "kv.revoke_lease":
                lease_entry = kv._leases.get(args[0])
                if lease_entry is not None:
                    await kv.revoke_lease(lease_entry[0])
                return True
            if method == "kv.watch_prefix":
                stream_id = next(self._stream_ids)
                watch = kv.watch_prefix(args[0])
                watches[stream_id] = watch
                pumps.append(spawn_logged(pump_watch(stream_id, watch)))
                return stream_id
            if method == "kv.cancel_watch":
                watch = watches.pop(args[0], None)
                if watch:
                    watch.cancel()
                return True
            if method == "bus.publish":
                # subscriber count, so remote publishers can detect a dark
                # subject (worker mid-resubscribe) and re-publish
                return await bus.publish(args[0], args[1], args[2])
            if method == "bus.subscribe":
                stream_id = next(self._stream_ids)
                sub = await bus.subscribe(args[0], args[1])
                subs[stream_id] = sub
                pumps.append(spawn_logged(pump_sub(stream_id, sub)))
                return stream_id
            if method == "bus.unsubscribe":
                sub = subs.pop(args[0], None)
                if sub:
                    await sub.unsubscribe()
                return True
            if method == "bus.request":
                return await bus.request(args[0], args[1], args[2])
            if method == "bus.queue_publish":
                await bus.queue_publish(args[0], args[1])
                return True
            if method == "bus.queue_pop":
                return await bus.queue_pop(args[0], args[1])
            if method == "bus.queue_pop_meta":
                item = await bus.queue_pop_meta(args[0], args[1])
                # tuple → list for the codec; age is the SERVER's own
                # enqueue→pop measurement (skew-free for remote consumers)
                return None if item is None else [item[0], item[1]]
            if method == "bus.queue_len":
                return await bus.queue_len(args[0])
            if method == "bus.object_put":
                await bus.object_put(args[0], args[1], args[2])
                return True
            if method == "bus.object_get":
                return await bus.object_get(args[0], args[1])
            if method == "bus.object_delete":
                return await bus.object_delete(args[0], args[1])
            if method == "ping":
                return "pong"
            raise ValueError(f"unknown method {method}")

        async def handle_request(frame: dict) -> None:
            try:
                result = await dispatch(frame["m"], frame.get("a", []))
                await send({"i": frame["i"], "ok": True, "r": result})
            except Exception as exc:  # noqa: BLE001
                # request-scoped RPCs carry a trace frame stamp: name the
                # request so a failed publish is attributable end-to-end
                trace = frame_trace(frame)
                logger.warning(
                    "rpc %s failed: %r%s", frame.get("m"), exc,
                    f" (trace {trace.trace_id})" if trace is not None else "",
                )
                await send({"i": frame["i"], "ok": False, "e": repr(exc)})

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                # blocking calls (queue_pop, bus.request) must not stall the
                # connection; every request runs as its own task.
                spawn_logged(handle_request(frame))
        finally:
            self._client_writers.discard(writer)
            for watch in watches.values():
                watch.cancel()
            for sub in subs.values():
                await sub.unsubscribe()
            for pump in pumps:
                pump.cancel()
            writer.close()


async def run_server(host: str = "127.0.0.1", port: int = 2379) -> None:
    server = ControlPlaneServer(host, port)
    await server.start()
    await server.serve_forever()
