"""Control-plane address resolution.

- ``memory`` or ``memory://<name>`` — shared in-process instance.
- ``host:port``                     — TCP client to a dynctl server.
"""

from __future__ import annotations

from dynamo_tpu.runtime.controlplane.interface import ControlPlane
from dynamo_tpu.runtime.controlplane.memory import MemoryControlPlane


async def connect_control_plane(address: str) -> ControlPlane:
    if address == "memory" or address.startswith("memory://"):
        name = address.removeprefix("memory://") or "default"
        if name == "memory":
            name = "default"
        return MemoryControlPlane.named(name)
    host, _, port = address.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"invalid control plane address: {address!r}")
    from dynamo_tpu.runtime.controlplane.client import RemoteControlPlane

    plane = RemoteControlPlane(host, int(port))
    await plane.connect()
    return plane
