"""TCP client for the dynctl control-plane server.

Implements the same ``KeyValueStore`` / ``MessageBus`` interfaces as the
memory backend by msgpack-RPC over one multiplexed connection.  Leases are
kept alive by a background task at ttl/3 cadence (reference: etcd lease
keep-alive, lib/runtime/src/transports/etcd.rs:44-170).
"""

from __future__ import annotations

import asyncio
import itertools

from dynamo_tpu.runtime.controlplane.interface import (
    ControlPlane,
    KVEntry,
    KeyValueStore,
    Lease,
    Message,
    MessageBus,
    Subscription,
    Watch,
    WatchEvent,
    WatchEventType,
)
from dynamo_tpu.runtime.controlplane.wire import (
    kv_entry_from_wire,
    pack_frame,
    read_frame,
    with_trace,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("runtime.controlplane.client")


class RpcConnection:
    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._req_ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, object] = {}  # stream_id -> Watch | Subscription
        self._unrouted: dict[int, list[dict]] = {}  # pushes racing registration
        self._read_task: asyncio.Task | None = None
        self._write_lock = asyncio.Lock()
        self._closed = False

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._read_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                if "i" in frame:  # rpc response
                    fut = self._pending.pop(frame["i"], None)
                    if fut is not None and not fut.done():
                        if frame["ok"]:
                            fut.set_result(frame.get("r"))
                        else:
                            fut.set_exception(RuntimeError(frame.get("e", "rpc error")))
                elif "s" in frame:  # stream push
                    self._route_push(frame)
        finally:
            # cleanup must run on ANY exit (clean EOF, socket errors read_frame
            # doesn't catch, corrupt frames) or pending calls and watches hang
            self._closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("control plane connection lost"))
            self._pending.clear()
            for target in self._streams.values():
                if isinstance(target, Watch):
                    # surface the loss to ready() waiters and iterators
                    # instead of ending the stream silently
                    target._fail(ConnectionError("control plane connection lost"))
                elif isinstance(target, Subscription):
                    target._closed = True
                    target._queue.put_nowait(None)
            self._streams.clear()

    def register_stream(self, stream_id: int, target: object) -> None:
        """Attach a local stream handle; flush any pushes that raced it."""
        if self._closed:
            # the read loop already died (its cleanup ran before we got
            # here): fail the target now or it would hang forever
            if isinstance(target, Watch):
                target._fail(ConnectionError("control plane connection lost"))
            elif isinstance(target, Subscription):
                target._closed = True
                target._queue.put_nowait(None)
            return
        self._streams[stream_id] = target
        for frame in self._unrouted.pop(stream_id, []):
            self._route_push(frame)

    def _route_push(self, frame: dict) -> None:
        target = self._streams.get(frame["s"])
        if target is None:
            # push arrived before the caller registered the handle (the rpc
            # response and the first events race through the read loop)
            self._unrouted.setdefault(frame["s"], []).append(frame)
            return
        kind, data = frame["t"], frame["d"]
        if kind == "close":
            self._streams.pop(frame["s"], None)
            if isinstance(target, Watch):
                target._close()
            elif isinstance(target, Subscription):
                target._queue.put_nowait(None)
        elif kind == "kv" and isinstance(target, Watch):
            target._emit(
                WatchEvent(WatchEventType(data["type"]), kv_entry_from_wire(data["entry"]))
            )
        elif kind == "sync" and isinstance(target, Watch):
            target._emit_sync()
        elif kind == "bus" and isinstance(target, Subscription):
            target._deliver(
                Message(subject=data["subject"], payload=data["payload"], reply_to=data["reply_to"])
            )

    async def call(
        self, method: str, *args, timeout: float | None = 30.0, trace=None
    ):
        if self._closed:
            raise ConnectionError("control plane connection closed")
        req_id = next(self._req_ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._write_lock:
            assert self._writer is not None
            # request-scoped RPCs (e.g. the push router's envelope publish)
            # stamp their TraceContext on the frame so dynctl can attribute
            # failures to the request trace
            self._writer.write(
                pack_frame(with_trace({"i": req_id, "m": method, "a": list(args)}, trace))
            )
            await self._writer.drain()
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            self._writer.close()


class RemoteKV(KeyValueStore):
    def __init__(self, conn: RpcConnection):
        self._conn = conn
        self._keepalive_tasks: dict[int, asyncio.Task] = {}

    async def put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        return await self._conn.call("kv.put", key, value, lease_id)

    async def create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        return await self._conn.call("kv.create", key, value, lease_id)

    async def get(self, key: str) -> KVEntry | None:
        result = await self._conn.call("kv.get", key)
        return kv_entry_from_wire(result) if result else None

    async def get_prefix(self, prefix: str) -> list[KVEntry]:
        return [kv_entry_from_wire(d) for d in await self._conn.call("kv.get_prefix", prefix)]

    async def delete(self, key: str) -> bool:
        return await self._conn.call("kv.delete", key)

    async def delete_prefix(self, prefix: str) -> int:
        return await self._conn.call("kv.delete_prefix", prefix)

    async def grant_lease(self, ttl: float) -> Lease:
        lease_id = await self._conn.call("kv.grant_lease", ttl)
        lease = Lease(id=lease_id, ttl=ttl)
        self._keepalive_tasks[lease_id] = asyncio.ensure_future(self._keepalive_loop(lease))
        return lease

    async def _keepalive_loop(self, lease: Lease) -> None:
        """Auto keep-alive (the client owns the heartbeat, like etcd's
        lease keep-alive stream)."""
        try:
            while not lease.revoked:
                await asyncio.sleep(max(lease.ttl / 3.0, 0.1))
                ok = await self._conn.call("kv.keep_alive", lease.id)
                if not ok:
                    lease._revoked.set()
                    return
        except (ConnectionError, asyncio.CancelledError):
            lease._revoked.set()

    async def keep_alive(self, lease: Lease) -> None:
        await self._conn.call("kv.keep_alive", lease.id)

    async def revoke_lease(self, lease: Lease) -> None:
        task = self._keepalive_tasks.pop(lease.id, None)
        if task is not None:
            task.cancel()
        lease._revoked.set()
        await self._conn.call("kv.revoke_lease", lease.id)

    def watch_prefix(self, prefix: str) -> Watch:
        watch = Watch()

        async def _start() -> None:
            try:
                stream_id = await self._conn.call("kv.watch_prefix", prefix)
            except Exception as exc:  # noqa: BLE001 — a dropped connection
                # here must not leave ready() waiters hanging forever
                logger.warning("watch_prefix(%s) failed to start: %s", prefix, exc)
                watch._fail(exc)
                return
            self._conn.register_stream(stream_id, watch)
            watch._stream_id = stream_id  # type: ignore[attr-defined]
            if watch._cancelled:  # cancelled before registration completed
                await _release(stream_id)

        async def _release(stream_id: int) -> None:
            self._conn._streams.pop(stream_id, None)
            try:
                await self._conn.call("kv.cancel_watch", stream_id)
            except ConnectionError:
                pass

        original_cancel = watch.cancel

        def cancel() -> None:
            # release the server-side registration too; otherwise the server
            # keeps serializing and sending every matching event forever
            original_cancel()
            stream_id = getattr(watch, "_stream_id", None)
            if stream_id is not None:
                asyncio.ensure_future(_release(stream_id))

        watch.cancel = cancel  # type: ignore[method-assign]
        asyncio.ensure_future(_start())
        return watch


class RemoteBus(MessageBus):
    def __init__(self, conn: RpcConnection):
        self._conn = conn
        # set once a server rejects bus.queue_pop_meta (older dynctl)
        self._pop_meta_unsupported = False

    async def publish(
        self, subject: str, payload: bytes, reply_to: str | None = None, trace=None
    ) -> None:
        await self._conn.call("bus.publish", subject, payload, reply_to, trace=trace)

    async def subscribe(self, subject: str, queue_group: str | None = None) -> Subscription:
        sub = Subscription(subject)
        stream_id = await self._conn.call("bus.subscribe", subject, queue_group)
        self._conn.register_stream(stream_id, sub)
        original_unsub = sub.unsubscribe

        async def _unsub() -> None:
            self._conn._streams.pop(stream_id, None)
            try:
                await self._conn.call("bus.unsubscribe", stream_id)
            except ConnectionError:
                pass
            await original_unsub()

        sub.unsubscribe = _unsub  # type: ignore[method-assign]
        return sub

    async def request(self, subject: str, payload: bytes, timeout: float = 5.0) -> bytes:
        return await self._conn.call("bus.request", subject, payload, timeout, timeout=timeout + 5)

    async def queue_publish(self, queue: str, payload: bytes) -> None:
        await self._conn.call("bus.queue_publish", queue, payload)

    async def queue_pop(self, queue: str, timeout: float | None = None) -> bytes | None:
        rpc_timeout = None if timeout is None else timeout + 5
        return await self._conn.call("bus.queue_pop", queue, timeout, timeout=rpc_timeout)

    async def queue_pop_meta(
        self, queue: str, timeout: float | None = None
    ) -> tuple[bytes, float | None] | None:
        rpc_timeout = None if timeout is None else timeout + 5
        if not self._pop_meta_unsupported:
            try:
                item = await self._conn.call(
                    "bus.queue_pop_meta", queue, timeout, timeout=rpc_timeout
                )
            except RuntimeError as err:
                if "unknown method" not in str(err):
                    raise
                # pre-queue_pop_meta dynctl server: degrade to the
                # documented (payload, None) contract and remember, so a
                # mixed-version fleet pays one failed round trip, not one
                # per pop
                self._pop_meta_unsupported = True
            else:
                # age is measured on the server's clock at pop time; the
                # reply's transit adds a little un-counted staleness, which
                # errs toward treating items as fresh (a wasted prefill,
                # never dropped traffic)
                return None if item is None else (item[0], item[1])
        payload = await self.queue_pop(queue, timeout)
        return None if payload is None else (payload, None)

    async def queue_len(self, queue: str) -> int:
        return await self._conn.call("bus.queue_len", queue)

    async def object_put(self, bucket: str, name: str, data: bytes) -> None:
        await self._conn.call("bus.object_put", bucket, name, data, timeout=120)

    async def object_get(self, bucket: str, name: str) -> bytes | None:
        return await self._conn.call("bus.object_get", bucket, name, timeout=120)

    async def object_delete(self, bucket: str, name: str) -> bool:
        return await self._conn.call("bus.object_delete", bucket, name)


class RemoteControlPlane(ControlPlane):
    def __init__(self, host: str, port: int):
        self._conn = RpcConnection(host, port)
        self.kv = RemoteKV(self._conn)
        self.bus = RemoteBus(self._conn)

    async def connect(self) -> None:
        await self._conn.connect()
        await self._conn.call("ping")

    async def close(self) -> None:
        for task in self.kv._keepalive_tasks.values():
            task.cancel()
        await self._conn.close()
