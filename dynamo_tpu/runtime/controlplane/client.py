"""TCP client for the dynctl control-plane server.

Implements the same ``KeyValueStore`` / ``MessageBus`` interfaces as the
memory backend by msgpack-RPC over one multiplexed connection.  Leases are
kept alive by a background task at ttl/3 cadence (reference: etcd lease
keep-alive, lib/runtime/src/transports/etcd.rs:44-170).

Self-healing (on by default, ``DYN_CP_RECONNECT=0`` restores fail-fast):
a lost connection triggers automatic reconnect with capped exponential
backoff + jitter, and a successful reconnect runs *resync* before any
ordinary call is unblocked —

- leases are re-granted (new id, same TTL) and every key that was attached
  to them is re-put, so registered instances/models survive a control-plane
  restart instead of vanishing until their processes restart;
- watches are re-established with **snapshot resync**: consumers keep their
  original ``Watch`` handle and see the fresh snapshot replayed as PUTs
  plus synthetic DELETEs for keys that vanished while disconnected — a
  consistent view, never a dead stream;
- subscriptions re-subscribe (messages published during the gap are lost,
  matching NATS core semantics).

In-flight RPCs at the moment of loss fail with ``ConnectionError``; calls
issued while disconnected wait (within their timeout) for resync to finish.
"""

from __future__ import annotations

import asyncio
import itertools
import os

from dynamo_tpu.robustness import counters
from dynamo_tpu.robustness.faults import CP_RECV, CP_SEND, FAULTS
from dynamo_tpu.robustness.retry import Backoff
from dynamo_tpu.runtime.controlplane.interface import (
    WATCH_SYNC,
    ControlPlane,
    KVEntry,
    KeyValueStore,
    Lease,
    Message,
    MessageBus,
    Subscription,
    Watch,
    WatchEvent,
    WatchEventType,
)
from dynamo_tpu.runtime.controlplane.wire import (
    kv_entry_from_wire,
    pack_frame,
    read_frame,
    with_trace,
)
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged
from dynamo_tpu.utils import knobs

logger = get_logger("runtime.controlplane.client")


def _reconnect_default() -> bool:
    return knobs.get("DYN_CP_RECONNECT")


class RpcConnection:
    def __init__(self, host: str, port: int, *, reconnect: bool | None = None):
        self.host, self.port = host, port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._req_ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, object] = {}  # stream_id -> Watch | Subscription
        self._unrouted: dict[int, list[dict]] = {}  # pushes racing registration
        self._read_task: asyncio.Task | None = None
        self._write_lock = asyncio.Lock()
        self._closed = False
        self.reconnect_enabled = (
            _reconnect_default() if reconnect is None else reconnect
        )
        # _transport_up: a socket is open (resync-internal calls may flow).
        # _ready: resync finished (ordinary calls may flow).  Split so the
        # re-grant/re-subscribe traffic cannot deadlock behind itself.
        self._transport_up = asyncio.Event()
        self._ready = asyncio.Event()
        self._gen = 0  # bumps on every successful (re)connect
        self._reconnect_task: asyncio.Task | None = None
        # insertion-ordered: the lease hook (registered at plane creation)
        # runs before every stream hook, so re-established watches snapshot
        # the re-put keys
        self._resync_hooks: dict[object, object] = {}
        self.reconnects_total = 0

    # -- resync registry ---------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def gen(self) -> int:
        return self._gen

    def add_resync_hook(self, key: object, hook) -> None:
        self._resync_hooks[key] = hook

    def remove_resync_hook(self, key: object) -> None:
        self._resync_hooks.pop(key, None)

    # -- lifecycle ---------------------------------------------------------
    async def connect(self) -> None:
        await self._open_transport()
        self._ready.set()

    async def _open_transport(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._gen += 1
        self._transport_up.set()
        self._read_task = spawn_logged(self._read_loop())

    async def _read_loop(self) -> None:
        reader = self._reader
        assert reader is not None
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                # chaos seam: a triggered cp.recv drops this frame AND the
                # connection (the cleanup below runs), exercising the full
                # reconnect/resync path deterministically
                FAULTS.check(CP_RECV)
                if "i" in frame:  # rpc response
                    fut = self._pending.pop(frame["i"], None)
                    if fut is not None and not fut.done():
                        if frame["ok"]:
                            fut.set_result(frame.get("r"))
                        else:
                            fut.set_exception(RuntimeError(frame.get("e", "rpc error")))
                elif "s" in frame:  # stream push
                    self._route_push(frame)
        except Exception as exc:  # noqa: BLE001 — nobody awaits this task;
            # an unswallowed socket/codec/injected error would surface as
            # "Task exception was never retrieved" at GC instead of here
            logger.warning("control-plane read loop ended: %r", exc)
        finally:
            # cleanup must run on ANY exit (clean EOF, socket errors read_frame
            # doesn't catch, corrupt frames) or pending calls and watches hang
            self._transport_up.clear()
            self._ready.clear()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("control plane connection lost"))
            self._pending.clear()
            for target in self._streams.values():
                if isinstance(target, Watch):
                    # surface the loss to ready() waiters and iterators
                    # instead of ending the stream silently
                    target._fail(ConnectionError("control plane connection lost"))
                elif isinstance(target, Subscription):
                    target._closed = True
                    target._queue.put_nowait(None)
            self._streams.clear()
            self._unrouted.clear()
            if self._writer is not None:
                self._writer.close()
            if not self._closed:
                if self.reconnect_enabled:
                    self._ensure_reconnect()
                else:
                    self._closed = True  # fail-fast mode: terminal loss

    def _ensure_reconnect(self) -> None:
        if self._reconnect_task is None or self._reconnect_task.done():
            self._reconnect_task = spawn_logged(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        backoff = Backoff.from_env("DYN_CP_RECONNECT", initial=0.05, max_delay=2.0)
        while not self._closed:
            await asyncio.sleep(backoff.next())
            try:
                await self._open_transport()
            except OSError as exc:
                if backoff.attempts in (1, 2) or backoff.attempts % 20 == 0:
                    logger.warning(
                        "control-plane reconnect to %s:%d failed (attempt %d): %r",
                        self.host, self.port, backoff.attempts, exc,
                    )
                continue
            try:
                await self.call("ping", timeout=5.0, wait_ready=False)
                for hook in list(self._resync_hooks.values()):
                    try:
                        await hook()
                    except (ConnectionError, OSError, asyncio.TimeoutError):
                        raise
                    except Exception:  # noqa: BLE001 — one buggy hook must
                        # not strand the connection in permanent
                        # "reconnecting" nor starve the remaining hooks
                        logger.exception("resync hook failed; continuing degraded")
            except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
                logger.warning("control-plane resync interrupted (retrying): %r", exc)
                if self._writer is not None:
                    self._writer.close()
                await asyncio.sleep(0)  # let the read loop's cleanup run
                continue
            self.reconnects_total += 1
            counters.incr("dyn_cp_reconnects_total")
            self._ready.set()
            logger.info(
                "control plane reconnected to %s:%d after %d attempt(s) "
                "(%d lease/stream resync hooks)",
                self.host, self.port, backoff.attempts, len(self._resync_hooks),
            )
            return

    def register_stream(self, stream_id: int, target: object) -> None:
        """Attach a local stream handle; flush any pushes that raced it."""
        if self._closed or not self._transport_up.is_set():
            # the read loop already died (its cleanup ran before we got
            # here): fail the target now or it would hang forever
            if isinstance(target, Watch):
                target._fail(ConnectionError("control plane connection lost"))
            elif isinstance(target, Subscription):
                target._closed = True
                target._queue.put_nowait(None)
            return
        self._streams[stream_id] = target
        for frame in self._unrouted.pop(stream_id, []):
            self._route_push(frame)

    def _route_push(self, frame: dict) -> None:
        target = self._streams.get(frame["s"])
        if target is None:
            # push arrived before the caller registered the handle (the rpc
            # response and the first events race through the read loop)
            self._unrouted.setdefault(frame["s"], []).append(frame)
            return
        kind, data = frame["t"], frame["d"]
        if kind == "close":
            self._streams.pop(frame["s"], None)
            if isinstance(target, Watch):
                target._close()
            elif isinstance(target, Subscription):
                target._queue.put_nowait(None)
        elif kind == "kv" and isinstance(target, Watch):
            target._emit(
                WatchEvent(WatchEventType(data["type"]), kv_entry_from_wire(data["entry"]))
            )
        elif kind == "sync" and isinstance(target, Watch):
            target._emit_sync()
        elif kind == "bus" and isinstance(target, Subscription):
            target._deliver(
                Message(subject=data["subject"], payload=data["payload"], reply_to=data["reply_to"])
            )

    async def call(
        self, method: str, *args, timeout: float | None = 30.0, trace=None,
        wait_ready: bool = True,
    ):
        """Issue one RPC.  ``wait_ready=False`` is for resync-internal
        traffic: it requires only an open socket and never waits (waiting
        on ``_ready`` from inside resync would deadlock)."""
        FAULTS.check(CP_SEND, method=method)
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else loop.time() + timeout
        gate = self._ready if wait_ready else self._transport_up
        while not gate.is_set():
            if self._closed or not self.reconnect_enabled or not wait_ready:
                raise ConnectionError("control plane connection closed")
            remaining = None if deadline is None else deadline - loop.time()
            if remaining is not None and remaining <= 0:
                raise ConnectionError(
                    f"control plane unavailable after {timeout:.0f}s (reconnecting)"
                )
            try:
                # bounded wait so a close() while we sleep is noticed
                await asyncio.wait_for(
                    gate.wait(), 0.5 if remaining is None else min(remaining, 0.5)
                )
            except asyncio.TimeoutError:
                continue
        if self._closed:
            raise ConnectionError("control plane connection closed")
        req_id = next(self._req_ids)
        fut: asyncio.Future = loop.create_future()
        self._pending[req_id] = fut
        try:
            async with self._write_lock:
                writer = self._writer
                if writer is None or writer.is_closing():
                    raise ConnectionError("control plane connection lost")
                # request-scoped RPCs (e.g. the push router's envelope
                # publish) stamp their TraceContext on the frame so dynctl
                # can attribute failures to the request trace
                writer.write(
                    pack_frame(with_trace({"i": req_id, "m": method, "a": list(args)}, trace))
                )
                await writer.drain()
            if deadline is None:
                return await fut
            return await asyncio.wait_for(fut, max(deadline - loop.time(), 0.01))
        finally:
            self._pending.pop(req_id, None)

    async def close(self) -> None:
        self._closed = True
        if self._reconnect_task is not None:
            self._reconnect_task.cancel()
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            self._writer.close()


class _ReconnectingWatch:
    """Driver keeping one consumer-facing ``Watch`` alive across
    reconnects.

    It tracks the consumer's live key view (key → last seen value).  After
    a re-establishment, the fresh server snapshot is forwarded as ordinary
    PUTs (consumers upsert idempotently) and, at the snapshot boundary,
    keys that existed before the outage but not in the new snapshot are
    emitted as synthetic DELETEs carrying their last-known value — so a
    consumer that parses deleted entries (instance views, model watchers)
    can identify what vanished."""

    def __init__(self, conn: RpcConnection, prefix: str, outer: Watch):
        self.conn = conn
        self.prefix = prefix
        self.outer = outer
        self._known: dict[str, bytes] = {}
        self._inner: Watch | None = None
        self._inner_changed = asyncio.Event()
        self._stream_id: int | None = None
        self._established_once = False

    def install(self) -> None:
        original_cancel = self.outer.cancel

        def cancel() -> None:
            # release the server-side registration too; otherwise the server
            # keeps serializing and sending every matching event forever
            original_cancel()
            self.conn.remove_resync_hook(self)
            if self._stream_id is not None and not self.conn.closed:
                spawn_logged(self._release())

        self.outer.cancel = cancel  # type: ignore[method-assign]
        self.conn.add_resync_hook(self, self.resync)
        spawn_logged(self._run())

    async def _establish(self, *, wait_ready: bool) -> None:
        stream_id = await self.conn.call(
            "kv.watch_prefix", self.prefix, wait_ready=wait_ready
        )
        inner = Watch()
        self.conn.register_stream(stream_id, inner)
        self._stream_id = stream_id
        self._inner = inner
        self._inner_changed.set()
        if self.outer._cancelled:  # cancelled before registration completed
            await self._release()

    async def resync(self) -> None:
        """Called by the connection's reconnect loop (transport up, resync
        in progress)."""
        if self.outer._cancelled:
            self.conn.remove_resync_hook(self)
            return
        await self._establish(wait_ready=False)

    async def _release(self) -> None:
        stream_id, self._stream_id = self._stream_id, None
        if stream_id is None:
            return
        self.conn._streams.pop(stream_id, None)
        inner = self._inner
        if inner is not None:
            inner._close()  # wake the pump if it is blocked on this stream
        try:
            await self.conn.call("kv.cancel_watch", stream_id, wait_ready=False)
        except (ConnectionError, RuntimeError):
            pass

    async def _run(self) -> None:
        try:
            await self._establish(wait_ready=True)
        except Exception as exc:  # noqa: BLE001 — a dead plane at startup
            # must not leave ready() waiters hanging forever
            logger.warning("watch_prefix(%s) failed to start: %s", self.prefix, exc)
            self.conn.remove_resync_hook(self)
            self.outer._fail(exc)
            return
        await self._pump()

    async def _wait_inner(self) -> Watch | None:
        while self._inner is None:
            if self.outer._cancelled:
                return None
            if self.conn.closed:
                self.outer._fail(ConnectionError("control plane connection closed"))
                return None
            try:
                await asyncio.wait_for(self._inner_changed.wait(), 0.5)
            except asyncio.TimeoutError:
                continue
        return self._inner

    async def _pump(self) -> None:
        while True:
            inner = await self._wait_inner()
            if inner is None:
                return
            replay = self._established_once
            self._established_once = True
            snapshot: set[str] = set()
            in_snapshot = True
            while True:
                item = await inner._queue.get()
                if item is None or self.outer._cancelled:
                    break
                if item is WATCH_SYNC:
                    if in_snapshot:
                        in_snapshot = False
                        if replay:
                            # synthetic resync: anything the consumer still
                            # believes exists but the new snapshot lacks was
                            # deleted (or lease-reaped) during the outage
                            for key in [k for k in self._known if k not in snapshot]:
                                value = self._known.pop(key)
                                self.outer._emit(
                                    WatchEvent(
                                        WatchEventType.DELETE,
                                        KVEntry(key=key, value=value),
                                    )
                                )
                        self.outer._emit_sync()
                    continue
                entry = item.entry
                if item.type == WatchEventType.PUT:
                    if in_snapshot:
                        snapshot.add(entry.key)
                    self._known[entry.key] = entry.value
                else:
                    self._known.pop(entry.key, None)
                self.outer._emit(item)
            if self.outer._cancelled:
                self.conn.remove_resync_hook(self)
                await self._release()
                return
            if inner._error is None:
                # clean server-side close: propagate (not a failure)
                self.conn.remove_resync_hook(self)
                self.outer._close()
                return
            if self.conn.closed or not self.conn.reconnect_enabled:
                self.conn.remove_resync_hook(self)
                self.outer._fail(inner._error)
                return
            # connection lost: park until the reconnect loop re-establishes
            # this watch via resync().  Guarded — resync may already have
            # swapped a fresh inner in while we drained the dead one, and
            # clobbering it would park this pump forever.
            if self._inner is inner:
                self._inner = None
                self._inner_changed.clear()


class _ReconnectingSub:
    """Driver keeping one consumer-facing ``Subscription`` alive across
    reconnects (plain resubscribe; gap messages are lost, as with NATS
    core subscriptions)."""

    def __init__(
        self, conn: RpcConnection, subject: str, queue_group: str | None,
        outer: Subscription,
    ):
        self.conn = conn
        self.subject = subject
        self.queue_group = queue_group
        self.outer = outer
        self._inner: Subscription | None = None
        self._inner_changed = asyncio.Event()
        self._stream_id: int | None = None

    async def start(self) -> None:
        """First establishment; errors propagate to the subscribe() caller."""
        await self._establish(wait_ready=True)
        self.conn.add_resync_hook(self, self.resync)
        spawn_logged(self._pump())

        original_unsub = self.outer.unsubscribe

        async def _unsub() -> None:
            self.conn.remove_resync_hook(self)
            await self._release()
            await original_unsub()

        self.outer.unsubscribe = _unsub  # type: ignore[method-assign]

    async def _establish(self, *, wait_ready: bool) -> None:
        stream_id = await self.conn.call(
            "bus.subscribe", self.subject, self.queue_group, wait_ready=wait_ready
        )
        inner = Subscription(self.subject)
        self.conn.register_stream(stream_id, inner)
        self._stream_id = stream_id
        self._inner = inner
        self._inner_changed.set()
        if self.outer._closed:
            await self._release()

    async def resync(self) -> None:
        if self.outer._closed:
            self.conn.remove_resync_hook(self)
            return
        await self._establish(wait_ready=False)

    async def _release(self) -> None:
        stream_id, self._stream_id = self._stream_id, None
        if stream_id is None:
            return
        self.conn._streams.pop(stream_id, None)
        inner = self._inner
        if inner is not None and not inner._closed:
            inner._closed = True
            inner._queue.put_nowait(None)  # wake the pump
        try:
            await self.conn.call("bus.unsubscribe", stream_id, wait_ready=False)
        except (ConnectionError, RuntimeError):
            pass

    async def _wait_inner(self) -> Subscription | None:
        while self._inner is None:
            if self.outer._closed or self.conn.closed:
                return None
            try:
                await asyncio.wait_for(self._inner_changed.wait(), 0.5)
            except asyncio.TimeoutError:
                continue
        return self._inner

    async def _pump(self) -> None:
        while True:
            inner = await self._wait_inner()
            if inner is None:
                if not self.outer._closed:
                    self.outer._closed = True
                    self.outer._queue.put_nowait(None)
                self.conn.remove_resync_hook(self)
                return
            while True:
                msg = await inner._queue.get()
                if msg is None or self.outer._closed:
                    break
                self.outer._deliver(msg)
            if self.outer._closed:
                self.conn.remove_resync_hook(self)
                await self._release()
                return
            if not inner._closed or self.conn.closed or not self.conn.reconnect_enabled:
                # clean server-side close, or a terminal connection loss:
                # end the consumer stream
                self.conn.remove_resync_hook(self)
                self.outer._closed = True
                self.outer._queue.put_nowait(None)
                return
            # connection lost: park until resync() resubscribes (guarded —
            # resync may already have swapped a fresh inner in)
            if self._inner is inner:
                self._inner = None
                self._inner_changed.clear()


class _LeaseRecord:
    """Everything needed to resurrect one lease after a reconnect: the
    (mutable) Lease handle and the keys attached to it."""

    __slots__ = ("lease", "keys")

    def __init__(self, lease: Lease):
        self.lease = lease
        self.keys: dict[str, bytes] = {}


class RemoteKV(KeyValueStore):
    def __init__(self, conn: RpcConnection):
        self._conn = conn
        self._keepalive_tasks: dict[int, asyncio.Task] = {}  # id(lease) -> task
        self._lease_records: dict[int, _LeaseRecord] = {}  # id(lease) -> record
        # leases re-grant FIRST on reconnect (hook registered before any
        # watch/sub driver exists), so re-established watches snapshot the
        # re-put keys
        conn.add_resync_hook("kv.leases", self._resync_leases)

    def _record_for(self, lease_id: int) -> _LeaseRecord | None:
        for record in self._lease_records.values():
            if record.lease.id == lease_id and not record.lease.revoked:
                return record
        return None

    async def put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        rev = await self._conn.call("kv.put", key, value, lease_id)
        if lease_id:
            record = self._record_for(lease_id)
            if record is not None:
                record.keys[key] = value
        return rev

    async def create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        created = await self._conn.call("kv.create", key, value, lease_id)
        if created and lease_id:
            record = self._record_for(lease_id)
            if record is not None:
                record.keys[key] = value
        return created

    async def get(self, key: str) -> KVEntry | None:
        result = await self._conn.call("kv.get", key)
        return kv_entry_from_wire(result) if result else None

    async def get_prefix(self, prefix: str) -> list[KVEntry]:
        return [kv_entry_from_wire(d) for d in await self._conn.call("kv.get_prefix", prefix)]

    async def delete(self, key: str) -> bool:
        deleted = await self._conn.call("kv.delete", key)
        for record in self._lease_records.values():
            record.keys.pop(key, None)
        return deleted

    async def delete_prefix(self, prefix: str) -> int:
        n = await self._conn.call("kv.delete_prefix", prefix)
        for record in self._lease_records.values():
            for key in [k for k in record.keys if k.startswith(prefix)]:
                del record.keys[key]
        return n

    async def grant_lease(self, ttl: float) -> Lease:
        lease_id = await self._conn.call("kv.grant_lease", ttl)
        lease = Lease(id=lease_id, ttl=ttl)
        self._lease_records[id(lease)] = _LeaseRecord(lease)
        self._keepalive_tasks[id(lease)] = spawn_logged(
            self._keepalive_loop(lease)
        )
        return lease

    async def _regrant(self, record: _LeaseRecord, *, wait_ready: bool) -> None:
        """Grant a fresh lease for a record and re-attach its keys.  The
        Lease handle mutates in place (callers keep their reference; the
        keep-alive loop heartbeats whatever id it currently holds)."""
        lease = record.lease
        new_id = await self._conn.call("kv.grant_lease", lease.ttl, wait_ready=wait_ready)
        lease.id = new_id
        for key, value in list(record.keys.items()):
            await self._conn.call("kv.put", key, value, new_id, wait_ready=wait_ready)
        logger.info(
            "re-granted lease %d (ttl=%.1fs) with %d attached key(s)",
            new_id, lease.ttl, len(record.keys),
        )

    async def _resync_leases(self) -> None:
        for record in list(self._lease_records.values()):
            if record.lease.revoked:
                continue
            await self._regrant(record, wait_ready=False)

    async def _keepalive_loop(self, lease: Lease) -> None:
        """Auto keep-alive (the client owns the heartbeat, like etcd's
        lease keep-alive stream).  A dropped connection marks the lease for
        re-grant on reconnect (the resync hook performs it) instead of
        silently ending the heartbeat — pre-fix, workers stayed registered
        until TTL reap and then vanished forever."""
        record = self._lease_records.get(id(lease))
        try:
            while not lease.revoked:
                await asyncio.sleep(max(lease.ttl / 3.0, 0.1))
                try:
                    ok = await self._conn.call("kv.keep_alive", lease.id)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    # TimeoutError covers the half-open-TCP partition: the
                    # transport never reports loss, the RPC just times out —
                    # the heartbeat must survive that too, not die silently
                    if self._conn.closed or not self._conn.reconnect_enabled:
                        lease._revoked.set()
                        return
                    continue  # reconnect's resync re-grants; keep beating
                except RuntimeError as exc:  # server-side error frame
                    logger.warning("keep_alive for lease %d failed: %s", lease.id, exc)
                    continue
                if not ok and record is not None and not lease.revoked:
                    # the server does not know this lease (restart raced
                    # resync, or TTL reaped during a partition): re-grant in
                    # place and re-attach our keys
                    try:
                        await self._regrant(record, wait_ready=True)
                    except (ConnectionError, OSError, asyncio.TimeoutError, RuntimeError):
                        continue
        except asyncio.CancelledError:
            lease._revoked.set()

    async def keep_alive(self, lease: Lease) -> None:
        await self._conn.call("kv.keep_alive", lease.id)

    async def revoke_lease(self, lease: Lease) -> None:
        task = self._keepalive_tasks.pop(id(lease), None)
        if task is not None:
            task.cancel()
        self._lease_records.pop(id(lease), None)
        lease._revoked.set()
        await self._conn.call("kv.revoke_lease", lease.id)

    def watch_prefix(self, prefix: str) -> Watch:
        watch = Watch()
        _ReconnectingWatch(self._conn, prefix, watch).install()
        return watch


class RemoteBus(MessageBus):
    def __init__(self, conn: RpcConnection):
        self._conn = conn
        # set once a server rejects bus.queue_pop_meta (older dynctl)
        self._pop_meta_unsupported = False

    async def publish(
        self, subject: str, payload: bytes, reply_to: str | None = None, trace=None
    ) -> int | None:
        result = await self._conn.call("bus.publish", subject, payload, reply_to, trace=trace)
        # current dynctl returns the delivered-subscriber count; an older
        # server returns True (bool — "unknown", NOT a hard zero)
        return result if type(result) is int else None

    async def subscribe(self, subject: str, queue_group: str | None = None) -> Subscription:
        sub = Subscription(subject)
        driver = _ReconnectingSub(self._conn, subject, queue_group, sub)
        await driver.start()
        return sub

    async def request(self, subject: str, payload: bytes, timeout: float = 5.0) -> bytes:
        return await self._conn.call("bus.request", subject, payload, timeout, timeout=timeout + 5)

    async def queue_publish(self, queue: str, payload: bytes) -> None:
        await self._conn.call("bus.queue_publish", queue, payload)

    async def queue_pop(self, queue: str, timeout: float | None = None) -> bytes | None:
        rpc_timeout = None if timeout is None else timeout + 5
        return await self._conn.call("bus.queue_pop", queue, timeout, timeout=rpc_timeout)

    async def queue_pop_meta(
        self, queue: str, timeout: float | None = None
    ) -> tuple[bytes, float | None] | None:
        rpc_timeout = None if timeout is None else timeout + 5
        if not self._pop_meta_unsupported:
            try:
                item = await self._conn.call(
                    "bus.queue_pop_meta", queue, timeout, timeout=rpc_timeout
                )
            except RuntimeError as err:
                if "unknown method" not in str(err):
                    raise
                # pre-queue_pop_meta dynctl server: degrade to the
                # documented (payload, None) contract and remember, so a
                # mixed-version fleet pays one failed round trip, not one
                # per pop
                self._pop_meta_unsupported = True
            else:
                # age is measured on the server's clock at pop time; the
                # reply's transit adds a little un-counted staleness, which
                # errs toward treating items as fresh (a wasted prefill,
                # never dropped traffic)
                return None if item is None else (item[0], item[1])
        payload = await self.queue_pop(queue, timeout)
        return None if payload is None else (payload, None)

    async def queue_len(self, queue: str) -> int:
        return await self._conn.call("bus.queue_len", queue)

    async def object_put(self, bucket: str, name: str, data: bytes) -> None:
        await self._conn.call("bus.object_put", bucket, name, data, timeout=120)

    async def object_get(self, bucket: str, name: str) -> bytes | None:
        return await self._conn.call("bus.object_get", bucket, name, timeout=120)

    async def object_delete(self, bucket: str, name: str) -> bool:
        return await self._conn.call("bus.object_delete", bucket, name)


class RemoteControlPlane(ControlPlane):
    def __init__(self, host: str, port: int, *, reconnect: bool | None = None):
        self._conn = RpcConnection(host, port, reconnect=reconnect)
        self.kv = RemoteKV(self._conn)
        self.bus = RemoteBus(self._conn)

    @property
    def reconnects_total(self) -> int:
        """Successful reconnects on this plane's connection (also counted
        process-wide in ``dyn_cp_reconnects_total``)."""
        return self._conn.reconnects_total

    async def connect(self) -> None:
        await self._conn.connect()
        await self._conn.call("ping")

    async def close(self) -> None:
        for task in self.kv._keepalive_tasks.values():
            task.cancel()
        await self._conn.close()
