"""Network ingress: serving an endpoint instance.

The worker-side push endpoint (reference:
lib/runtime/src/pipeline/network/ingress/push_endpoint.rs:39-101): subscribes
the instance's bus subject, spawns a handler task per request, connects back
over TCP to stream responses, and tracks in-flight requests for graceful
drain on shutdown.
"""

from __future__ import annotations

import asyncio
import json
import time

import msgpack

from dynamo_tpu.observability import get_recorder
from dynamo_tpu.observability import flight as flight_obs
from dynamo_tpu.observability.trace import read_trace
from dynamo_tpu.robustness import counters
from dynamo_tpu.robustness.faults import FAULTS, WORKER_GENERATE
from dynamo_tpu.runtime.component import (
    Instance,
    ctl_subject,
    instance_key,
    stats_subject,
)
from dynamo_tpu.runtime.dataplane import ConnectionInfo, ResponseStreamSender
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineContext
from dynamo_tpu.utils import knobs
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("runtime.ingress")


class EndpointService:
    """A live, registered instance serving one engine."""

    def __init__(
        self,
        runtime,
        instance: Instance,
        engine: AsyncEngine,
        *,
        stats_handler=None,
        topo_role: str = "",
        topo_transfer_address: str = "",
        topo_slice: str | None = None,
    ):
        self.runtime = runtime
        self.instance = instance
        self.engine = engine
        self.stats_handler = stats_handler
        # topology plane: placement facts for this instance's TopologyCard
        # (published lease-scoped in start() when DYN_TOPO is on)
        self.topo_role = topo_role
        self.topo_transfer_address = topo_transfer_address
        self.topo_slice = topo_slice
        self._lease = None
        self._sub = None
        self._stats_sub = None
        self._ctl_sub = None
        self._tasks: set[asyncio.Task] = set()
        self._loop_task: asyncio.Task | None = None
        self._stats_task: asyncio.Task | None = None
        self._ctl_task: asyncio.Task | None = None
        self._draining = False
        self._drain_task: asyncio.Task | None = None
        self._stopped = False
        self._in_flight = 0
        self._arrived_total = 0
        self._last_arrival = 0.0  # event-loop time of the newest request
        self._handled_total = 0
        self._errors_total = 0
        self._drained = asyncio.Event()
        self._drained.set()
        self._started_at = time.time()

    # -- lifecycle ---------------------------------------------------------
    async def start(self, lease_ttl: float = 3.0) -> None:
        plane = self.runtime.plane
        self._lease = await plane.kv.grant_lease(lease_ttl)
        self._sub = await plane.bus.subscribe(self.instance.subject)
        self._stats_sub = await plane.bus.subscribe(stats_subject(self.instance.subject))
        self._ctl_sub = await plane.bus.subscribe(ctl_subject(self.instance.subject))
        self._loop_task = spawn_logged(self._serve_loop())
        self._stats_task = spawn_logged(self._stats_loop())
        self._ctl_task = spawn_logged(self._ctl_loop())
        self.runtime.register_keepalive(self._lease)
        # register *after* subscribing so no request can race the subscription
        await plane.kv.put(instance_key(self.instance), self.instance.to_json(), self._lease.id)
        if knobs.get("DYN_TOPO"):
            from dynamo_tpu.topology import local_card, publish_card

            await publish_card(self, local_card(
                self.instance.instance_id,
                transfer_address=self.topo_transfer_address,
                role=self.topo_role,
                slice_label=self.topo_slice,
            ))
        logger.info("serving %s (instance %x)", self.instance.subject, self.instance.instance_id)

    async def shutdown(self, *, drain_timeout: float | None = None) -> None:
        """Deregister, drain in-flight requests, stop accepting.

        Ordering matters: deregistering stops NEW routing decisions, but
        clients with a stale instance view keep publishing to this subject
        until their watch catches up — the subscription must stay open
        through the drain window or those requests are silently dropped
        and their callers wait out the rendezvous timeout (found by the
        runtime soak test's churn wave)."""
        if self._stopped:
            # a graceful drain already tore everything down (it leaves the
            # control loop alive just long enough to publish its reply)
            await self._close_ctl()
            return
        plane = self.runtime.plane
        await plane.kv.delete(instance_key(self.instance))
        if self._stats_sub is not None:
            await self._stats_sub.unsubscribe()
        if drain_timeout is None:
            drain_timeout = self.runtime.config.graceful_shutdown_timeout
        loop = asyncio.get_running_loop()
        deadline = loop.time() + drain_timeout
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                logger.warning(
                    "drain timeout: %d requests still in flight on %s",
                    self._in_flight,
                    self.instance.subject,
                )
                break
            try:
                await asyncio.wait_for(self._drained.wait(), remaining)
            except asyncio.TimeoutError:
                continue
            # an envelope may already sit in the subscription queue with no
            # handler task yet (invisible to in_flight/arrival counters):
            # yield so _serve_loop can spawn it, then require no live tasks
            await asyncio.sleep(0)
            if self._tasks or self._in_flight:
                continue
            # quiet period: in_flight hitting zero mid-burst is not done —
            # stale-view clients may still be publishing; only close the
            # subject once no new request ARRIVED for a beat (arrivals, not
            # completions: a request that arrives and fails connect-back
            # inside the window must still count as activity).  A service
            # whose last arrival is already older than the beat — including
            # one that never served — skips the sleep entirely.
            if loop.time() - self._last_arrival > 0.25:
                break
            before = self._arrived_total
            await asyncio.sleep(min(0.25, max(deadline - loop.time(), 0.0)))
            if (
                self._in_flight == 0
                and self._arrived_total == before
                and not self._tasks
            ):
                break
        if self._sub is not None:
            await self._sub.unsubscribe()
        await self._close_ctl()
        for task in (self._loop_task, self._stats_task):
            if task is not None:
                task.cancel()
        for task in list(self._tasks):
            task.cancel()
        if self._lease is not None:
            await plane.kv.revoke_lease(self._lease)
        self._stopped = True

    async def abort(self) -> None:
        """Crash-style teardown (chaos/worker-kill seam): no drain, no
        grace — the lease is revoked and every handler task is cancelled
        mid-stream, exactly like a process dying under a supervisor.  The
        cancelled handlers' error frames give the dispatcher its mid-stream
        failure to resume from."""
        plane = self.runtime.plane
        await plane.kv.delete(instance_key(self.instance))
        if self._sub is not None:
            await self._sub.unsubscribe()
        if self._stats_sub is not None:
            await self._stats_sub.unsubscribe()
        await self._close_ctl()
        for task in (self._loop_task, self._stats_task):
            if task is not None:
                task.cancel()
        tasks = [t for t in list(self._tasks) if not t.done()]
        for task in tasks:
            task.cancel()
        if tasks:
            await asyncio.wait(tasks, timeout=5)
        if self._lease is not None:
            await plane.kv.revoke_lease(self._lease)
        self._stopped = True

    async def _close_ctl(self) -> None:
        if self._ctl_sub is not None:
            await self._ctl_sub.unsubscribe()
            self._ctl_sub = None
        task, self._ctl_task = self._ctl_task, None
        if task is not None and task is not asyncio.current_task():
            task.cancel()

    # -- graceful drain ----------------------------------------------------
    async def drain(self, timeout_s: float | None = None) -> dict:
        """Empty this worker without killing any request.

        State machine: (1) admissions stop instantly — the instance key is
        deleted so routers stop picking us, and any stale-view envelope
        that still lands gets an immediate ``worker shutting down`` error
        frame the dispatcher treats as a safe pre-first-token retry;
        (2) in-flight requests get ~half the budget to finish naturally;
        (3) survivors are handed off — their handler tasks are cancelled,
        whose error frames the dispatcher resumes from its generation
        journal on a healthy peer; (4) the lease is revoked, so the
        instance is gone from every view BEFORE the process exits.

        Idempotent and concurrency-safe: every caller (dynctl, SIGTERM,
        planner scale-down, a racing shutdown) awaits the same underlying
        drain and gets the same result dict.
        """
        if self._drain_task is None:
            self._drain_task = asyncio.ensure_future(self._drain(timeout_s))
        return await asyncio.shield(self._drain_task)

    async def _drain(self, timeout_s: float | None) -> dict:
        if timeout_s is None or timeout_s <= 0:
            timeout_s = knobs.get("DYN_DRAIN_TIMEOUT_S")
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        deadline = t0 + timeout_s
        self._draining = True
        counters.incr("dyn_drain_started_total")
        # flight recorder: record the drain and snapshot the ring NOW —
        # the worker is about to empty and the pre-drain window is the
        # evidence an operator wants
        flight_obs.dump_all_on_drain(
            instance=f"{self.instance.instance_id:x}", in_flight=self._in_flight
        )
        span = get_recorder().start(
            "engine.drain", None, component="worker",
            attrs={"subject": self.instance.subject,
                   "instance": f"{self.instance.instance_id:x}",
                   "in_flight": self._in_flight,
                   "timeout_s": timeout_s},
        )
        plane = self.runtime.plane
        await plane.kv.delete(instance_key(self.instance))
        # phase 1 — natural completion: short sequences just finish
        natural_deadline = t0 + timeout_s * 0.5
        while (self._tasks or self._in_flight) and loop.time() < natural_deadline:
            try:
                await asyncio.wait_for(
                    self._drained.wait(),
                    max(min(0.1, natural_deadline - loop.time()), 0.01),
                )
            except asyncio.TimeoutError:
                pass
            await asyncio.sleep(0)  # let _serve_loop spawn queued envelopes
        # phase 2 — handoff: cancel survivors; their CancelledError path
        # sends "worker shutting down", which the dispatcher's journal
        # resumes on another worker with exactly-once delivery
        me = asyncio.current_task()
        handoff = [t for t in list(self._tasks) if t is not me and not t.done()]
        for task in handoff:
            task.cancel()
        if handoff:
            counters.incr("dyn_drain_handoff_total", len(handoff))
            await asyncio.wait(handoff, timeout=max(deadline - loop.time(), 0.5))
        emptied = not self._tasks and self._in_flight == 0
        # phase 3 — teardown: revoke the lease before anyone can exit us
        if self._sub is not None:
            await self._sub.unsubscribe()
        if self._stats_sub is not None:
            await self._stats_sub.unsubscribe()
        for task in (self._loop_task, self._stats_task):
            if task is not None and task is not me:
                task.cancel()
        if self._lease is not None:
            await plane.kv.revoke_lease(self._lease)
        self._stopped = True
        if emptied:
            counters.incr("dyn_drain_completed_total")
        result = {
            "op": "drain",
            "ok": emptied,
            "instance_id": f"{self.instance.instance_id:x}",
            "subject": self.instance.subject,
            "handed_off": len(handoff),
            "duration_s": round(loop.time() - t0, 3),
        }
        if span is not None:
            span.end(**{k: v for k, v in result.items() if k != "op"})
        logger.info(
            "drained %s: ok=%s handed_off=%d in %.2fs",
            self.instance.subject, emptied, len(handoff), result["duration_s"],
        )
        return result

    async def _ctl_loop(self) -> None:
        """Request/reply control verbs on ``_ctl.<subject>`` (dynctl drain)."""
        assert self._ctl_sub is not None
        async for msg in self._ctl_sub:
            try:
                op = json.loads(msg.payload.decode())
            except Exception:  # noqa: BLE001
                logger.warning("malformed ctl message on %s", self.instance.subject)
                continue
            if op.get("op") == "flight_dump":
                # on-demand flight dump (dynctl flight dump): write every
                # live recorder's ring and reply with the paths
                paths = flight_obs.dump_all("manual")
                if msg.reply_to:
                    await self.runtime.plane.bus.publish(
                        msg.reply_to,
                        json.dumps({
                            "op": "flight_dump",
                            "ok": True,
                            "instance_id": f"{self.instance.instance_id:x}",
                            "enabled": flight_obs.flight_enabled(),
                            "paths": [str(p) for p in paths],
                        }).encode(),
                    )
                continue
            if op.get("op") != "drain":
                if msg.reply_to:
                    await self.runtime.plane.bus.publish(
                        msg.reply_to,
                        json.dumps({"ok": False, "error": f"unknown op {op.get('op')!r}"}).encode(),
                    )
                continue
            result = await self.drain(op.get("timeout_s"))
            if msg.reply_to:
                await self.runtime.plane.bus.publish(
                    msg.reply_to, json.dumps(result).encode()
                )
            # the drain tore the instance down; close our own subscription
            # and exit (we cannot be cancelled mid-reply this way)
            await self._close_ctl()
            return

    # -- serving -----------------------------------------------------------
    async def _serve_loop(self) -> None:
        assert self._sub is not None
        async for msg in self._sub:
            try:
                envelope = msgpack.unpackb(msg.payload, raw=False)
            except Exception:  # noqa: BLE001
                logger.warning("malformed request envelope on %s", self.instance.subject)
                continue
            task = asyncio.ensure_future(self._handle(envelope))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    async def _handle(self, envelope: dict) -> None:
        control = envelope["c"]
        request = envelope["p"]
        ctx = EngineContext(control["id"])
        # propagated trace context: engine-side spans (queue/prefill/decode)
        # nest under this worker's handle span so one trace_id reassembles
        # the whole frontend → router → engine path
        wire_trace = read_trace(control)
        span = get_recorder().start(
            "worker.handle", wire_trace, component="worker",
            attrs={"subject": self.instance.subject,
                   "instance": f"{self.instance.instance_id:x}"},
        )
        ctx.trace = span.ctx if span is not None else None
        sender = ResponseStreamSender(ConnectionInfo.from_dict(control["ci"]), ctx)
        if self._draining or self._stopped:
            # admission stop: a stale-view client published to a draining
            # worker — connect back only to deliver the error frame, which
            # the dispatcher treats as a safe pre-first-token retry
            try:
                await sender.connect()
                await sender.error("worker shutting down")
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass
            if span is not None:
                span.end(status="error", error="draining: admission stopped")
            return
        self._in_flight += 1
        self._arrived_total += 1
        self._last_arrival = asyncio.get_running_loop().time()
        self._drained.clear()
        try:
            await sender.connect()
        # asyncio.TimeoutError: on py3.10 it is NOT an OSError subclass, and
        # connect()'s retry loop re-raises it after exhausting attempts — it
        # must not leak _in_flight
        except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
            logger.warning("connect-back failed for %s: %r", control["id"], exc)
            if span is not None:
                span.end(status="error", error=f"connect-back failed: {exc!r}")
            self._request_done()
            return
        try:
            # chaos seam: a worker failing before its engine produced
            # anything — the error frame reaches the frontend pre-first-
            # token, which re-dispatches to a healthy peer
            FAULTS.check(WORKER_GENERATE, request=control["id"])
            stream = await self.engine.generate(Context(request, ctx))
            items = 0
            async for item in stream:
                if ctx.is_killed:
                    break
                items += 1
                await sender.send(item)
            await sender.complete()
            self._handled_total += 1
            if span is not None:
                span.end(items=items, killed=ctx.is_killed)
        except asyncio.CancelledError:
            await sender.error("worker shutting down")
            if span is not None:
                span.end(status="error", error="worker shutting down")
            raise
        except Exception as exc:  # noqa: BLE001
            logger.exception("engine error on %s", self.instance.subject)
            self._errors_total += 1
            await sender.error(repr(exc))
            if span is not None:
                span.end(status="error", error=repr(exc))
        finally:
            self._request_done()

    def _request_done(self) -> None:
        self._in_flight -= 1
        if self._in_flight == 0:
            self._drained.set()

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        data = {
            "subject": self.instance.subject,
            "instance_id": self.instance.instance_id,
            "draining": self._draining,
            "in_flight": self._in_flight,
            "handled_total": self._handled_total,
            "errors_total": self._errors_total,
            "uptime_s": time.time() - self._started_at,
        }
        if self.stats_handler is not None:
            try:
                data["custom"] = self.stats_handler()
            except Exception:  # noqa: BLE001
                logger.exception("stats handler failed")
        return data

    async def _stats_loop(self) -> None:
        assert self._stats_sub is not None
        async for msg in self._stats_sub:
            if msg.reply_to:
                await self.runtime.plane.bus.publish(
                    msg.reply_to, json.dumps(self.stats()).encode()
                )
