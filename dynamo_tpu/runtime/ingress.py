"""Network ingress: serving an endpoint instance.

The worker-side push endpoint (reference:
lib/runtime/src/pipeline/network/ingress/push_endpoint.rs:39-101): subscribes
the instance's bus subject, spawns a handler task per request, connects back
over TCP to stream responses, and tracks in-flight requests for graceful
drain on shutdown.
"""

from __future__ import annotations

import asyncio
import json
import time

import msgpack

from dynamo_tpu.observability import get_recorder
from dynamo_tpu.observability.trace import read_trace
from dynamo_tpu.robustness.faults import FAULTS, WORKER_GENERATE
from dynamo_tpu.runtime.component import Instance, instance_key, stats_subject
from dynamo_tpu.runtime.dataplane import ConnectionInfo, ResponseStreamSender
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineContext
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("runtime.ingress")


class EndpointService:
    """A live, registered instance serving one engine."""

    def __init__(
        self,
        runtime,
        instance: Instance,
        engine: AsyncEngine,
        *,
        stats_handler=None,
    ):
        self.runtime = runtime
        self.instance = instance
        self.engine = engine
        self.stats_handler = stats_handler
        self._lease = None
        self._sub = None
        self._stats_sub = None
        self._tasks: set[asyncio.Task] = set()
        self._loop_task: asyncio.Task | None = None
        self._stats_task: asyncio.Task | None = None
        self._in_flight = 0
        self._arrived_total = 0
        self._last_arrival = 0.0  # event-loop time of the newest request
        self._handled_total = 0
        self._errors_total = 0
        self._drained = asyncio.Event()
        self._drained.set()
        self._started_at = time.time()

    # -- lifecycle ---------------------------------------------------------
    async def start(self, lease_ttl: float = 3.0) -> None:
        plane = self.runtime.plane
        self._lease = await plane.kv.grant_lease(lease_ttl)
        self._sub = await plane.bus.subscribe(self.instance.subject)
        self._stats_sub = await plane.bus.subscribe(stats_subject(self.instance.subject))
        self._loop_task = spawn_logged(self._serve_loop())
        self._stats_task = spawn_logged(self._stats_loop())
        self.runtime.register_keepalive(self._lease)
        # register *after* subscribing so no request can race the subscription
        await plane.kv.put(instance_key(self.instance), self.instance.to_json(), self._lease.id)
        logger.info("serving %s (instance %x)", self.instance.subject, self.instance.instance_id)

    async def shutdown(self, *, drain_timeout: float | None = None) -> None:
        """Deregister, drain in-flight requests, stop accepting.

        Ordering matters: deregistering stops NEW routing decisions, but
        clients with a stale instance view keep publishing to this subject
        until their watch catches up — the subscription must stay open
        through the drain window or those requests are silently dropped
        and their callers wait out the rendezvous timeout (found by the
        runtime soak test's churn wave)."""
        plane = self.runtime.plane
        await plane.kv.delete(instance_key(self.instance))
        if self._stats_sub is not None:
            await self._stats_sub.unsubscribe()
        if drain_timeout is None:
            drain_timeout = self.runtime.config.graceful_shutdown_timeout
        loop = asyncio.get_running_loop()
        deadline = loop.time() + drain_timeout
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                logger.warning(
                    "drain timeout: %d requests still in flight on %s",
                    self._in_flight,
                    self.instance.subject,
                )
                break
            try:
                await asyncio.wait_for(self._drained.wait(), remaining)
            except asyncio.TimeoutError:
                continue
            # an envelope may already sit in the subscription queue with no
            # handler task yet (invisible to in_flight/arrival counters):
            # yield so _serve_loop can spawn it, then require no live tasks
            await asyncio.sleep(0)
            if self._tasks or self._in_flight:
                continue
            # quiet period: in_flight hitting zero mid-burst is not done —
            # stale-view clients may still be publishing; only close the
            # subject once no new request ARRIVED for a beat (arrivals, not
            # completions: a request that arrives and fails connect-back
            # inside the window must still count as activity).  A service
            # whose last arrival is already older than the beat — including
            # one that never served — skips the sleep entirely.
            if loop.time() - self._last_arrival > 0.25:
                break
            before = self._arrived_total
            await asyncio.sleep(min(0.25, max(deadline - loop.time(), 0.0)))
            if (
                self._in_flight == 0
                and self._arrived_total == before
                and not self._tasks
            ):
                break
        if self._sub is not None:
            await self._sub.unsubscribe()
        for task in (self._loop_task, self._stats_task):
            if task is not None:
                task.cancel()
        for task in list(self._tasks):
            task.cancel()
        if self._lease is not None:
            await plane.kv.revoke_lease(self._lease)

    # -- serving -----------------------------------------------------------
    async def _serve_loop(self) -> None:
        assert self._sub is not None
        async for msg in self._sub:
            try:
                envelope = msgpack.unpackb(msg.payload, raw=False)
            except Exception:  # noqa: BLE001
                logger.warning("malformed request envelope on %s", self.instance.subject)
                continue
            task = asyncio.ensure_future(self._handle(envelope))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    async def _handle(self, envelope: dict) -> None:
        control = envelope["c"]
        request = envelope["p"]
        ctx = EngineContext(control["id"])
        # propagated trace context: engine-side spans (queue/prefill/decode)
        # nest under this worker's handle span so one trace_id reassembles
        # the whole frontend → router → engine path
        wire_trace = read_trace(control)
        span = get_recorder().start(
            "worker.handle", wire_trace, component="worker",
            attrs={"subject": self.instance.subject,
                   "instance": f"{self.instance.instance_id:x}"},
        )
        ctx.trace = span.ctx if span is not None else None
        sender = ResponseStreamSender(ConnectionInfo.from_dict(control["ci"]), ctx)
        self._in_flight += 1
        self._arrived_total += 1
        self._last_arrival = asyncio.get_running_loop().time()
        self._drained.clear()
        try:
            await sender.connect()
        # asyncio.TimeoutError: on py3.10 it is NOT an OSError subclass, and
        # connect()'s retry loop re-raises it after exhausting attempts — it
        # must not leak _in_flight
        except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
            logger.warning("connect-back failed for %s: %r", control["id"], exc)
            if span is not None:
                span.end(status="error", error=f"connect-back failed: {exc!r}")
            self._request_done()
            return
        try:
            # chaos seam: a worker failing before its engine produced
            # anything — the error frame reaches the frontend pre-first-
            # token, which re-dispatches to a healthy peer
            FAULTS.check(WORKER_GENERATE, request=control["id"])
            stream = await self.engine.generate(Context(request, ctx))
            items = 0
            async for item in stream:
                if ctx.is_killed:
                    break
                items += 1
                await sender.send(item)
            await sender.complete()
            self._handled_total += 1
            if span is not None:
                span.end(items=items, killed=ctx.is_killed)
        except asyncio.CancelledError:
            await sender.error("worker shutting down")
            if span is not None:
                span.end(status="error", error="worker shutting down")
            raise
        except Exception as exc:  # noqa: BLE001
            logger.exception("engine error on %s", self.instance.subject)
            self._errors_total += 1
            await sender.error(repr(exc))
            if span is not None:
                span.end(status="error", error=repr(exc))
        finally:
            self._request_done()

    def _request_done(self) -> None:
        self._in_flight -= 1
        if self._in_flight == 0:
            self._drained.set()

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        data = {
            "subject": self.instance.subject,
            "instance_id": self.instance.instance_id,
            "in_flight": self._in_flight,
            "handled_total": self._handled_total,
            "errors_total": self._errors_total,
            "uptime_s": time.time() - self._started_at,
        }
        if self.stats_handler is not None:
            try:
                data["custom"] = self.stats_handler()
            except Exception:  # noqa: BLE001
                logger.exception("stats handler failed")
        return data

    async def _stats_loop(self) -> None:
        assert self._stats_sub is not None
        async for msg in self._stats_sub:
            if msg.reply_to:
                await self.runtime.plane.bus.publish(
                    msg.reply_to, json.dumps(self.stats()).encode()
                )
