"""Network egress: endpoint clients and the push router.

Mirrors the reference's client/egress stack (reference:
lib/runtime/src/component/client.rs, pipeline/network/egress/push_router.rs):
a ``Client`` tracks live instances (static list or dynamic KV watch); a
``PushRouter`` picks an instance per request (random / round-robin / direct),
registers a local TCP response stream, and pushes the request envelope to the
instance's bus subject.
"""

from __future__ import annotations

import asyncio
import os
import enum
import random
import time
import uuid

import msgpack

from dynamo_tpu.observability import get_recorder
from dynamo_tpu.observability.trace import stamp_trace
from dynamo_tpu.robustness import counters
from dynamo_tpu.runtime.component import Endpoint, Instance, instances_prefix
from dynamo_tpu.runtime.dataplane import PendingStream
from dynamo_tpu.runtime.controlplane.interface import WatchEventType
from dynamo_tpu.runtime.engine import Context, EngineContext, ResponseStream
from dynamo_tpu.runtime.migration import MigrationCoordinator
from dynamo_tpu.runtime.resume import GenerationJournal, dedupe_stream
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged
from dynamo_tpu.utils import knobs

logger = get_logger("runtime.client")


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    KV = "kv"  # KV-cache-aware; scheduling provided by dynamo_tpu.llm.kv_router


class Client:
    """Tracks instances of one endpoint; generates requests against them."""

    def __init__(
        self,
        runtime,
        endpoint: Endpoint,
        *,
        static_instances: list[Instance] | None = None,
    ):
        self.runtime = runtime
        self.endpoint = endpoint
        self._static = static_instances is not None
        self._instances: dict[int, Instance] = {
            i.instance_id: i for i in (static_instances or [])
        }
        self._watch = None
        self._watch_task: asyncio.Task | None = None
        self._changed = asyncio.Event()
        # instance-removal hooks (sync callables, instance_id arg): the
        # migration coordinator uses the DELETE event — fired the moment a
        # drain deletes its instance key — to move survivors off the worker
        # while its natural-completion window is still open
        self.on_instance_removed: list = []

    async def start(self) -> None:
        if self._static:
            return
        prefix = instances_prefix(
            self.endpoint.component.namespace.name,
            self.endpoint.component.name,
            self.endpoint.name,
        )
        self._watch = self.runtime.plane.kv.watch_prefix(prefix)
        self._watch_task = spawn_logged(self._watch_loop())
        # Don't return until the watch's initial snapshot has been applied:
        # a request served before this sees an empty instance view even
        # though workers are registered (startup race).
        await self._watch.ready()

    async def _watch_loop(self) -> None:
        assert self._watch is not None
        try:
            async for event in self._watch:
                try:
                    inst = Instance.from_json(event.entry.value)
                except Exception:  # noqa: BLE001
                    continue
                if event.type == WatchEventType.PUT:
                    self._instances[inst.instance_id] = inst
                else:
                    self._instances.pop(inst.instance_id, None)
                    for hook in list(self.on_instance_removed):
                        try:
                            hook(inst.instance_id)
                        except Exception:  # noqa: BLE001
                            logger.exception("instance-removed hook failed")
                self._changed.set()
                self._changed = asyncio.Event()
        except ConnectionError as exc:
            # instance view is stale from here on; requests keep flowing to
            # the last-known instances rather than failing hard
            logger.warning("%s instance watch lost: %s", self.endpoint.path, exc)

    async def close(self) -> None:
        if self._watch is not None:
            self._watch.cancel()
        if self._watch_task is not None:
            self._watch_task.cancel()

    # -- instance views ----------------------------------------------------
    @property
    def instances(self) -> list[Instance]:
        return list(self._instances.values())

    @property
    def instance_ids(self) -> list[int]:
        return list(self._instances.keys())

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> list[Instance]:
        deadline = asyncio.get_running_loop().time() + timeout
        while len(self._instances) < n:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"{self.endpoint.path}: {len(self._instances)}/{n} instances after {timeout}s"
                )
            changed = self._changed
            try:
                await asyncio.wait_for(changed.wait(), min(remaining, 0.5))
            except asyncio.TimeoutError:
                pass
        return self.instances


class InstanceNotFound(RuntimeError):
    """Directly-addressed instance is no longer registered (deregistered or
    lease-reaped between scheduling and dispatch)."""


# Remote engine errors arrive as RuntimeError("remote engine error: <repr>")
# (dataplane error frames) — the repr is all we have to distinguish a dead
# worker from a request its engine deterministically rejects.
_TRANSIENT_STREAM_MARKERS = (
    "connection lost",          # transport died mid-stream (no error frame)
    "worker shutting down",     # drain raced the dispatch
    "ConnectionError",          # worker-side transport/injected failures,
    "ConnectionResetError",     # surfaced through the error frame's repr
    "BrokenPipeError",
    "TimeoutError",
    "OSError",
)


def _is_transient_stream_error(exc: BaseException) -> bool:
    """True for stream failures where re-dispatching can help (worker died,
    transport broke).  A deterministic application error (bad prompt,
    guided-decoding rejection) would fail identically on every peer —
    retrying it burns duplicate work and, worse, quarantines healthy
    workers over a poison request."""
    if isinstance(exc, (ConnectionError, OSError, asyncio.TimeoutError)):
        return True
    message = str(exc)
    return any(marker in message for marker in _TRANSIENT_STREAM_MARKERS)


class PushRouter:
    """Routes requests to instances and returns the response stream."""

    def __init__(self, client: Client, mode: RouterMode = RouterMode.RANDOM):
        self.client = client
        self.mode = mode
        self._rr = 0
        # quarantine shared by ALL routing modes: a worker that failed a
        # rendezvous is skipped until its deadline passes (a dead worker
        # stays in the instance view until its lease is reaped — or forever
        # if the watch was lost — and per-request exclusion alone would
        # re-pay the connect timeout on every other request)
        self.dark_ttl_s = knobs.get("DYN_DARK_WORKER_TTL_S")
        self._dark: dict[int, float] = {}  # instance_id -> retry-after monotonic
        # live-session migration (dynctl migrate / drain handoff / planner
        # defrag): journaled streams register with the coordinator so their
        # decode can be flipped to another worker mid-stream, exactly-once
        self.migrations: MigrationCoordinator | None = (
            MigrationCoordinator(self) if knobs.get("DYN_MIGRATE") else None
        )
        if self.migrations is not None:
            self.migrations.attach_client(client)

    @classmethod
    async def from_endpoint(
        cls, endpoint: Endpoint, mode: RouterMode = RouterMode.RANDOM
    ) -> "PushRouter":
        client = await endpoint.client()
        return cls(client, mode)

    def quarantine(self, instance_id: int) -> None:
        self._dark[instance_id] = time.monotonic() + self.dark_ttl_s

    def dark_instances(self) -> set[int]:
        """Currently-quarantined instance ids (expired entries dropped)."""
        now = time.monotonic()
        self._dark = {w: t for w, t in self._dark.items() if t > now}
        return set(self._dark)

    def healthy_ids(self, exclude: set[int] | None = None) -> list[int]:
        """Candidate instance ids under the shared routing policy:
        exclusion (failed THIS request) is hard; quarantine is soft —
        when every remaining candidate is quarantined, retry them rather
        than hard-failing a servable request."""
        ids = [
            w for w in self.client.instance_ids if w not in (exclude or set())
        ]
        if not ids:
            return []
        dark = self.dark_instances()
        healthy = [w for w in ids if w not in dark]
        return healthy or ids

    def _pick(
        self, instance_id: int | None, exclude: set[int] | None = None
    ) -> Instance | None:
        instances = self.client.instances
        if instance_id is not None:
            inst = self.client._instances.get(instance_id)
            if inst is None:
                raise InstanceNotFound(f"instance {instance_id:x} not found")
            return inst
        if not instances:
            raise RuntimeError(f"no instances available for {self.client.endpoint.path}")
        ids = set(self.healthy_ids(exclude))
        if not ids:
            return None  # every live instance already failed this request
        instances = [i for i in instances if i.instance_id in ids]
        if self.mode == RouterMode.ROUND_ROBIN:
            inst = instances[self._rr % len(instances)]
            self._rr += 1
            return inst
        return random.choice(instances)

    async def generate(
        self, request: Context[dict], *, instance_id: int | None = None
    ) -> ResponseStream[dict]:
        """Push ``request`` (a wire-dict) to an instance, return its stream.

        A rendezvous timeout fails over to another instance (reference:
        router modes re-pick per request, push_router.rs:111-155): a
        worker that died with its lease not yet reaped would otherwise
        surface a timeout to the caller while healthy peers sit idle.
        Safe because nothing has streamed before the rendezvous completes.
        Direct routing (explicit ``instance_id``) never fails over.

        After the rendezvous, a stream that fails BEFORE its first item is
        re-dispatched to another healthy instance (up to ``DYN_RETRY_MAX``
        times, counted in ``dyn_retries_total`` and visible as a
        ``dispatch.retry`` span): with zero items delivered the request
        provably had no observable effect on the client, so re-running it
        cannot duplicate output.

        A stream that fails AFTER its first item is *resumed* when the
        request is deterministic-replayable (greedy or seeded — see
        runtime/resume.py) and ``DYN_RESUME`` is on: the generation journal
        re-dispatches the original request plus a ``resume_from`` cursor,
        and a dedupe cursor over the new stream guarantees exactly-once
        token delivery.  Non-deterministic requests keep the honest
        truncation error.  Direct (pinned) dispatch never fails over
        pre-first-token — the KV router owns that reschedule — but DOES
        resume mid-stream, un-pinned: the affinity bet is already burned
        once the pinned worker died with the stream half-delivered.
        """
        tried: set[int] = set()
        pending, inst_id = await self._rendezvous(request, instance_id, tried)
        retry_max = knobs.get("DYN_RETRY_MAX")
        journal: GenerationJournal | None = None
        if retry_max > 0 and knobs.get("DYN_RESUME") and isinstance(request.data, dict):
            journal = GenerationJournal(request.data)
            if not journal.resumable:
                journal = None
        if instance_id is not None or retry_max <= 0:
            # direct routing keeps affinity decisions with the scheduler
            # (KV router does its own reschedule-excluding-failed failover),
            # so pre-first-token retries stay off here (retry_max=0 below)
            if journal is None:
                return ResponseStream(pending, request.ctx)
            return ResponseStream(
                self._stream_with_retry(
                    request, pending, inst_id, tried, 0,
                    journal=journal, resume_max=retry_max,
                ),
                request.ctx,
            )
        return ResponseStream(
            self._stream_with_retry(
                request, pending, inst_id, tried, retry_max,
                journal=journal, resume_max=retry_max if journal else 0,
            ),
            request.ctx,
        )

    async def _stream_with_retry(
        self, request: Context[dict], pending, inst_id: int, tried: set[int],
        retry_max: int, journal: GenerationJournal | None = None,
        resume_max: int = 0,
    ):
        retries = 0
        resumes = 0
        resume_counted = False
        # ``pending`` is what we iterate (possibly a dedupe wrapper);
        # ``raw`` is the underlying transport stream of the active hop —
        # the thing a migration flip must kill to release the source
        raw = pending
        handle = None
        if journal is not None and self.migrations is not None:
            handle = self.migrations.register(
                request.ctx.id, journal, request.ctx, inst_id
            )
        try:
            while True:
                streamed_any = False
                it = pending.__aiter__()
                try:
                    while True:
                        if handle is not None and handle.flip_pending():
                            if journal.finished:
                                # the finish item already reached the client;
                                # there is nothing left to move
                                handle.abort_flip("finished")
                            else:
                                # COMMIT — synchronous (no await between the
                                # pending check and ``done.set()``), so the
                                # coordinator's flip timeout can never observe
                                # a half-applied swap.  An item boundary IS a
                                # journal window boundary: the source decoded
                                # ``delta`` tokens past the snapshot, all
                                # delivered, and the destination regenerates
                                # exactly that window for the cursor to drop.
                                flip = handle.flip
                                delta = journal.total_recorded - flip.snap_total
                                old_raw, raw = raw, flip.dst_raw
                                inst_id = flip.dst_inst_id
                                handle.inst_id = inst_id
                                pending = dedupe_stream(
                                    raw, flip.payload_accepted + delta,
                                    ack_skip=delta,
                                )
                                it = pending.__aiter__()
                                handle.flip = None
                                flip.outcome = "committed"
                                flip.done.set()
                                # release the source: a data-plane control
                                # frame killing the worker-side context of
                                # the OLD hop only — the request context
                                # (and this stream) are untouched
                                spawn_logged(old_raw.send_control("kill"))
                        try:
                            item = await it.__anext__()
                        except StopAsyncIteration:
                            break
                        streamed_any = True
                        if journal is not None:
                            journal.record(item)
                        if (
                            isinstance(item, dict)
                            and isinstance(item.get("data"), dict)
                            and item["data"].get("finish_reason")
                        ):
                            # success is counted at the FINISH item, not at
                            # generator exhaustion: consumers stop pulling
                            # once they see the finish, so a post-loop
                            # increment may never run.  The journal releases
                            # its retained tokens here for the same reason.
                            if journal is not None:
                                journal.finish()
                            if resumes and not resume_counted:
                                resume_counted = True
                                counters.incr("dyn_resume_success_total")
                        yield item
                    if resumes and not resume_counted:
                        resume_counted = True
                        counters.incr("dyn_resume_success_total")
                    return
                except Exception as exc:  # noqa: BLE001 — retry decision below
                    if handle is not None:
                        # a flip prepared against the now-broken hop is void;
                        # the coordinator kills its pre-admitted destination
                        # and the ordinary resume machinery takes over —
                        # migration is never less safe than not migrating
                        handle.abort_flip()
                    if request.ctx.is_killed or not _is_transient_stream_error(exc):
                        raise
                    accepted = journal.accepted if journal is not None else []
                    if not streamed_any and not accepted:
                        # pre-first-token: safe plain re-dispatch
                        if retries >= retry_max:
                            raise
                        retries += 1
                        counters.incr("dyn_retries_total")
                        tried.add(inst_id)
                        self.quarantine(inst_id)
                        logger.warning(
                            "stream from instance %x failed pre-first-token (%s); "
                            "re-dispatching (retry %d/%d)",
                            inst_id, exc, retries, retry_max,
                        )
                        span = get_recorder().start(
                            "dispatch.retry", getattr(request.ctx, "trace", None),
                            component="frontend",
                            attrs={
                                "failed_instance": f"{inst_id:x}",
                                "attempt": retries,
                                "error": repr(exc),
                            },
                        )
                        try:
                            pending, inst_id = await self._rendezvous(request, None, tried)
                        except BaseException as redispatch_exc:
                            if span is not None:
                                span.end(status="error", error=repr(redispatch_exc))
                            # surface the original stream failure; the re-dispatch
                            # failure (usually "no instances left") rides as cause
                            raise exc from redispatch_exc
                        if span is not None:
                            span.end(instance=f"{inst_id:x}")
                        raw = pending
                        if handle is not None:
                            handle.inst_id = inst_id
                        continue
                    # mid-stream: resume from the journal (or truncate honestly)
                    if journal is None or resumes >= resume_max:
                        raise
                    resumes += 1
                    journal.resumes = resumes
                    counters.incr("dyn_resume_attempts_total")
                    tried.add(inst_id)
                    self.quarantine(inst_id)
                    logger.warning(
                        "stream from instance %x failed after %d accepted "
                        "token(s) (%s); resuming (resume %d/%d)",
                        inst_id, len(accepted), exc, resumes, resume_max,
                    )
                    span = get_recorder().start(
                        "dispatch.resume", getattr(request.ctx, "trace", None),
                        component="frontend",
                        attrs={
                            "failed_instance": f"{inst_id:x}",
                            "accepted_tokens": len(accepted),
                            "attempt": resumes,
                            "error": repr(exc),
                        },
                    )
                    # un-pinned re-dispatch of the ORIGINAL request + cursor; a
                    # resume-aware engine continues (and acks), everything else
                    # replays — riding the prefix cache — and the dedupe cursor
                    # drops the replayed prefix
                    resumed = Context(journal.resume_request(), request.ctx)
                    try:
                        raw, inst_id = await self._rendezvous(resumed, None, tried)
                    except BaseException as redispatch_exc:
                        if span is not None:
                            span.end(status="error", error=repr(redispatch_exc))
                        raise exc from redispatch_exc
                    if span is not None:
                        span.end(instance=f"{inst_id:x}")
                    if handle is not None:
                        handle.inst_id = inst_id
                    pending = dedupe_stream(raw, len(accepted))
        finally:
            if handle is not None:
                self.migrations.unregister(handle)

    async def _rendezvous(
        self, request: Context[dict], instance_id: int | None, tried: set[int]
    ) -> "tuple[PendingStream, int]":
        """One dispatch: pick an instance, publish the envelope, await the
        worker's connect-back.  Fails over across instances (``tried`` is
        shared with the caller's retry policy so a retry never lands on an
        instance this request already burned)."""
        runtime = self.client.runtime
        server = await runtime.data_server()
        ctx = request.ctx
        connect_timeout = knobs.get("DYN_CONNECT_TIMEOUT_S")
        # quarantined instances get a short probe window instead of the
        # full connect timeout: during a full-fleet outage healthy_ids
        # returns the dark set rather than hard-failing, and without this a
        # request would serially re-pay 30s per dark instance — a latency
        # storm instead of a fast, diagnosable failure
        dark_probe_timeout = min(
            connect_timeout, knobs.get("DYN_DARK_PROBE_TIMEOUT_S")
        )
        # hard cap on TOTAL rendezvous time across failovers; generation
        # time is unbounded as ever — this only bounds how long a request
        # can hunt for a worker that will talk to it.  The default scales
        # with the connect timeout so raising DYN_CONNECT_TIMEOUT_S (e.g.
        # for first-compile rendezvous on a loaded CI box) is never
        # silently undone by a smaller fixed budget.
        budget = knobs.get("DYN_RENDEZVOUS_BUDGET_S") or 3.0 * connect_timeout
        t_start = time.monotonic()
        last_err: Exception | None = None
        dark_started: dict[int, float] = {}  # instance -> first dark publish
        dark_count = 0
        empty_since: float | None = None  # first empty-instance-view pick
        while True:
            remaining = budget - (time.monotonic() - t_start)
            if remaining <= 0 and last_err is not None:
                logger.warning(
                    "rendezvous budget %.0fs exhausted after %d instance(s)",
                    budget, len(tried),
                )
                break
            # bounded by exclusion, not a count: every live instance gets
            # one shot (3 dark + 2 healthy must reach the healthy ones)
            try:
                inst = self._pick(instance_id, exclude=tried)
                empty_since = None
            except InstanceNotFound:
                raise  # pinned dispatch: let KV routing reschedule at once
            except RuntimeError as exc:
                # EMPTY instance view — can be transient: a control-plane
                # resync replays synthetic deletes before the workers'
                # re-registrations land (observed driving a real 3-process
                # dynctl restart).  Wait it out briefly before giving up.
                now = time.monotonic()
                empty_since = empty_since if empty_since is not None else now
                if now - empty_since >= dark_probe_timeout or remaining <= 0:
                    raise last_err or exc
                await asyncio.sleep(0.2)
                continue
            if inst is None:
                break
            # expiry-aware: an EXPIRED quarantine entry must not demote a
            # recovered worker to the probe window (direct dispatch skips
            # healthy_ids, so nothing else prunes on this path)
            attempt_timeout = (
                dark_probe_timeout
                if self._dark.get(inst.instance_id, 0.0) > time.monotonic()
                else connect_timeout
            )
            # every attempt (including the first) honors the budget: an
            # operator setting a budget below the connect timeout chose
            # fail-fast semantics deliberately
            attempt_timeout = min(attempt_timeout, max(remaining, 0.1))
            # stream ids are per-hop AND per-attempt (a pipeline stage
            # reuses the request ctx, so ctx.id alone would collide on the
            # shared server; a late connect-back from a failed-over attempt
            # must find nothing and get killed)
            stream_id = uuid.uuid4().hex
            pending = server.register(stream_id, ctx)
            # per-attempt dispatch span: the worker's spans parent to it, so
            # a failed-over request shows every rendezvous it paid for
            dispatch = get_recorder().start(
                "dispatch", getattr(ctx, "trace", None), component="frontend",
                attrs={"instance": f"{inst.instance_id:x}", "subject": inst.subject},
            )
            control = stamp_trace(
                {"id": ctx.id, "ci": server.connection_info(stream_id).to_dict()},
                dispatch.ctx if dispatch is not None else None,
            )
            envelope = msgpack.packb(
                {"c": control, "p": request.data}, use_bin_type=True
            )
            try:
                # the trace also stamps the control-plane transport frame
                # (remote planes), so dynctl can attribute publish failures
                delivered = await runtime.plane.bus.publish(
                    inst.subject, envelope,
                    trace=dispatch.ctx if dispatch is not None else None,
                )
                if delivered == 0:
                    # nobody received the envelope: the worker is dead (its
                    # lease will reap shortly and the watch prunes it) or
                    # mid-resubscribe after a control-plane reconnect.
                    # Re-publish soon instead of burning the full rendezvous
                    # timeout waiting for a connect-back that cannot come —
                    # found by driving a real multi-process dynctl restart.
                    server.unregister(stream_id)
                    if dispatch is not None:
                        dispatch.end(status="error", error="subject dark (no subscriber)")
                    last_err = TimeoutError(
                        f"no subscriber on {inst.subject} — worker dead, or "
                        "mid-resubscribe after a control-plane reconnect"
                    )
                    now = time.monotonic()
                    already_quarantined = self._dark.get(inst.instance_id, 0.0) > now
                    first_dark = dark_started.setdefault(inst.instance_id, now)
                    if already_quarantined or now - first_dark >= dark_probe_timeout:
                        # confirmed-dead subject (it was already suspect, or
                        # stayed dark past the probe window): same remedy as
                        # a rendezvous timeout — quarantine and fail over;
                        # pinned dispatch raises so KV routing reschedules
                        tried.add(inst.instance_id)
                        self.quarantine(inst.instance_id)
                        if instance_id is not None:
                            raise last_err from None
                        logger.warning("%s; failing over", last_err)
                        continue
                    # freshly dark: likely a resubscribe gap, not a death —
                    # re-publish within the probe window
                    dark_count += 1
                    if dark_count in (1, 2) or dark_count % 8 == 0:
                        logger.warning("%s; re-publishing", last_err)
                    await asyncio.sleep(0.25)
                    continue
                # rendezvous: wait for the worker to connect back before
                # returning the stream (the reference awaits the prologue)
                await asyncio.wait_for(pending.connected.wait(), timeout=attempt_timeout)
            except asyncio.TimeoutError:
                if pending.connected.is_set():
                    # the connect-back won the race with wait_for's timer
                    # (both fire in the same loop pass): the stream is
                    # live — failing over here would run the request twice
                    self._dark.pop(inst.instance_id, None)
                    self._end_dispatch(dispatch, pending)
                    return pending, inst.instance_id
                server.unregister(stream_id)
                if dispatch is not None:
                    dispatch.end(status="error", error="rendezvous timeout")
                tried.add(inst.instance_id)
                self.quarantine(inst.instance_id)
                # a bare TimeoutError is undiagnosable from the frontend;
                # name the instance and the usual causes (observed: a
                # request envelope the worker's codec rejected)
                last_err = TimeoutError(
                    f"no data-plane connect-back from instance "
                    f"{inst.instance_id:x} ({inst.subject}) within "
                    f"{attempt_timeout:.0f}s — worker dead/overloaded, or it "
                    "rejected the request envelope (check worker logs for "
                    "'malformed request')"
                )
                if instance_id is not None:
                    raise last_err from None
                logger.warning("%s; failing over", last_err)
                continue
            except ConnectionError as exc:
                # control-plane blip mid-publish: not the instance's fault.
                # Don't burn it from this request's candidate set — back off
                # briefly (the plane client is reconnecting underneath) and
                # re-dispatch; the rendezvous budget bounds the healing wait
                server.unregister(stream_id)
                if dispatch is not None:
                    dispatch.end(status="error", error=repr(exc))
                last_err = exc
                if instance_id is not None:
                    raise
                logger.warning("publish to %s failed (%s); retrying dispatch", inst.subject, exc)
                await asyncio.sleep(0.1)
                continue
            except BaseException as exc:
                # includes caller cancellation mid-rendezvous: the pending
                # registration must not leak (a later connect-back to an
                # unknown stream gets killed instead of streaming into an
                # orphaned queue)
                server.unregister(stream_id)
                if dispatch is not None:
                    dispatch.end(status="error", error=repr(exc))
                raise
            # successful rendezvous clears any quarantine: one transient
            # overload blip must not idle a recovered worker for the TTL
            self._dark.pop(inst.instance_id, None)
            self._end_dispatch(dispatch, pending)
            return pending, inst.instance_id
        if last_err is None:
            # every live instance is already in ``tried`` (the pre-first-
            # token retry path re-enters with the failed set pre-populated)
            raise RuntimeError(
                f"no instances left to dispatch {self.client.endpoint.path} "
                f"({len(tried)} already failed this request)"
            )
        raise last_err

    @staticmethod
    def _end_dispatch(dispatch, pending) -> None:
        """Close a rendezvous span, cross-linking the worker-side span id
        the connect-back prologue carried — the explicit edge between the
        frontend's dispatch attempt and the worker.handle span that served
        it (robust even if either side's buffer later drops a span)."""
        if dispatch is None:
            return
        if pending.trace is not None:
            dispatch.end(worker_span=pending.trace.span_id)
        else:
            dispatch.end()

    async def generate_direct(self, request: Context[dict], instance_id: int) -> ResponseStream[dict]:
        return await self.generate(request, instance_id=instance_id)


class RemoteEngine:
    """AsyncEngine facade over a PushRouter (so pipelines can ``.link`` a
    remote endpoint transparently)."""

    def __init__(self, router: PushRouter, *, instance_id: int | None = None):
        self.router = router
        self.instance_id = instance_id

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        return await self.router.generate(request, instance_id=self.instance_id)
