"""Typed pipeline node graph.

The reference's pipeline layer (lib/runtime/src/pipeline/nodes.rs:1-351)
composes typed nodes — a frontend Source, chained Operators, and a terminal
Sink — and lets a pipeline be CUT at any edge into network-separated
segments (SegmentSource/SegmentSink).  This is the same model over our
streaming-engine contract (`runtime/engine.py`):

- ``source()`` starts a chain; ``.link(op)`` appends an Operator;
  ``.link(engine)`` terminates it with any AsyncEngine and returns the
  runnable pipeline.
- ``SegmentSink`` serves the downstream half of a cut pipeline on a
  component endpoint; ``segment_source`` connects the upstream half to it
  through the push router — the process-boundary edge is just another link.

Links are validated at composition time (an unterminated chain cannot
generate; a terminated chain cannot be extended), which is the Python
rendering of the reference's compile-time edge typing.
"""

from __future__ import annotations

from typing import Any, Generic

from dynamo_tpu.runtime.engine import (
    AsyncEngine,
    Context,
    Operator,
    Req,
    Resp,
    ResponseStream,
)


class PipelineChain(Generic[Req, Resp]):
    """A partially- or fully-linked chain of pipeline nodes."""

    def __init__(self, operators: list[Operator], engine: AsyncEngine | None = None):
        self._operators = operators
        self._engine = engine

    @property
    def terminated(self) -> bool:
        return self._engine is not None

    def link(self, node: "Operator | AsyncEngine") -> "PipelineChain":
        """Append an Operator, or terminate with an engine (Sink)."""
        if self.terminated:
            raise ValueError("pipeline already terminated by a sink")
        if isinstance(node, Operator):
            return PipelineChain([*self._operators, node])
        if not hasattr(node, "generate"):
            raise TypeError(
                f"link() takes an Operator or an AsyncEngine, got {type(node).__name__}"
            )
        # fold operators around the sink from the inside out
        engine: AsyncEngine = node
        for op in reversed(self._operators):
            engine = op.wrap(engine)
        return PipelineChain([], engine)

    async def generate(self, request: Context[Req]) -> ResponseStream[Resp]:
        if not self.terminated:
            raise ValueError(
                "pipeline not terminated: .link(engine) a sink before generating"
            )
        return await self._engine.generate(request)


def source() -> PipelineChain:
    """Start a typed pipeline chain (the frontend Source node)."""
    return PipelineChain([])


class SegmentSink:
    """Downstream half of a cut pipeline: serve a chain (or bare engine) on
    a component endpoint so remote segment-sources can link to it
    (reference: SegmentSink in pipeline/nodes.rs — the network edge)."""

    def __init__(self, endpoint, chain: "PipelineChain | AsyncEngine"):
        self.endpoint = endpoint
        if isinstance(chain, PipelineChain):
            if not chain.terminated:
                raise ValueError("segment sink needs a terminated chain")
        elif not hasattr(chain, "generate"):
            raise TypeError(
                f"segment sink takes a chain or engine, got {type(chain).__name__}"
            )
        self.engine = chain
        self._service = None

    async def start(self, **serve_kwargs: Any):
        self._service = await self.endpoint.serve(self.engine, **serve_kwargs)
        return self._service

    async def stop(self) -> None:
        if self._service is not None:
            await self._service.shutdown()
            self._service = None


async def segment_source(endpoint, *, router_mode=None) -> AsyncEngine:
    """Upstream half of a cut pipeline: an engine that forwards requests to
    the remote SegmentSink through the push router (the client side of the
    network edge).  Use its result as the sink of the local chain:
    ``source().link(op).link(await segment_source(ep))``."""
    from dynamo_tpu.runtime.client import PushRouter, RemoteEngine, RouterMode

    router = await PushRouter.from_endpoint(
        endpoint, router_mode or RouterMode.ROUND_ROBIN
    )
    return RemoteEngine(router)
