from dynamo_tpu.runtime.engine import (
    AsyncEngine,
    Context,
    EngineContext,
    Operator,
    ResponseStream,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime

__all__ = [
    "AsyncEngine",
    "Context",
    "EngineContext",
    "Operator",
    "ResponseStream",
    "DistributedRuntime",
]
