"""Two-part frame codec.

Length-prefixed (header, payload) frames used on data-plane TCP streams
(reference: lib/runtime/src/pipeline/network/codec/two_part.rs).  The header
is a small msgpack map (control/typing), the payload is opaque bytes.

Layout: ``u32 header_len | u32 payload_len | header | payload`` (big-endian).
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass

import msgpack

_PREFIX = struct.Struct("!II")
MAX_HEADER = 1 << 20          # 1 MiB
MAX_PAYLOAD = 1 << 31         # 2 GiB

# Frame headers carry a TraceContext wire dict under the shared reserved
# key (observability.trace.TRACE_WIRE_KEY) so data-plane streams stay
# correlatable with the request that opened them.  One canonical
# stamp/decode pair serves every transport.
from dynamo_tpu.observability.trace import (  # noqa: E402 (re-export)
    read_trace as extract_trace,
    stamp_trace as attach_trace,
)


@dataclass
class TwoPartMessage:
    header: dict
    payload: bytes = b""


def encode_frame(msg: TwoPartMessage) -> bytes:
    header = msgpack.packb(msg.header, use_bin_type=True)
    return _PREFIX.pack(len(header), len(msg.payload)) + header + msg.payload


def read_two_part_sync(sock) -> TwoPartMessage | None:
    """Blocking-socket twin of ``read_two_part`` (used by sync Storage
    clients that run under ``asyncio.to_thread``)."""

    def recv_exact(n: int) -> bytes | None:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    prefix = recv_exact(_PREFIX.size)
    if prefix is None:
        return None
    header_len, payload_len = _PREFIX.unpack(prefix)
    if header_len > MAX_HEADER or payload_len > MAX_PAYLOAD:
        raise ValueError(f"oversized frame: header={header_len} payload={payload_len}")
    header = recv_exact(header_len)
    if header is None:
        return None
    payload = recv_exact(payload_len) if payload_len else b""
    if payload is None:
        return None
    return TwoPartMessage(header=msgpack.unpackb(header, raw=False), payload=payload)


async def read_two_part(reader: asyncio.StreamReader) -> TwoPartMessage | None:
    try:
        prefix = await reader.readexactly(_PREFIX.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    header_len, payload_len = _PREFIX.unpack(prefix)
    if header_len > MAX_HEADER or payload_len > MAX_PAYLOAD:
        raise ValueError(f"oversized frame: header={header_len} payload={payload_len}")
    try:
        header = await reader.readexactly(header_len)
        payload = await reader.readexactly(payload_len) if payload_len else b""
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return TwoPartMessage(header=msgpack.unpackb(header, raw=False), payload=payload)
