"""Leader/worker barrier for multi-node engine bring-up.

KV-store rendezvous (reference: lib/runtime/src/utils/leader_worker_barrier.rs
— LeaderBarrier :153 posts data and waits for N workers; WorkerBarrier :237
reads it and checks in).  Used to coordinate multi-host JAX process groups
(``jax.distributed.initialize`` addresses flow through the barrier data).
"""

from __future__ import annotations

import asyncio
import json

from dynamo_tpu.runtime.component import ROOT_PATH
from dynamo_tpu.runtime.controlplane.interface import KeyValueStore, WatchEventType
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("runtime.barrier")


def _barrier_prefix(barrier_id: str) -> str:
    return f"{ROOT_PATH}barriers/{barrier_id}/"


class LeaderBarrier:
    """Leader posts payload, waits until ``num_workers`` check in."""

    def __init__(self, kv: KeyValueStore, barrier_id: str, num_workers: int):
        self.kv = kv
        self.barrier_id = barrier_id
        self.num_workers = num_workers

    async def sync(self, data: dict, *, timeout: float = 120.0, lease_id: int = 0) -> list[str]:
        prefix = _barrier_prefix(self.barrier_id)
        created = await self.kv.create(prefix + "leader", json.dumps(data).encode(), lease_id)
        if not created:
            raise RuntimeError(f"barrier {self.barrier_id} already has a leader")
        workers: set[str] = set()
        watch = self.kv.watch_prefix(prefix + "workers/")
        try:
            async with asyncio.timeout(timeout):
                async for event in watch:
                    if event.type != WatchEventType.PUT:
                        continue
                    workers.add(event.entry.key.rsplit("/", 1)[-1])
                    if len(workers) >= self.num_workers:
                        return sorted(workers)
        except TimeoutError:
            raise TimeoutError(
                f"barrier {self.barrier_id}: {len(workers)}/{self.num_workers} workers"
            ) from None
        finally:
            watch.cancel()
        return sorted(workers)


class WorkerBarrier:
    """Worker waits for the leader's payload, then checks in."""

    def __init__(self, kv: KeyValueStore, barrier_id: str, worker_id: str):
        self.kv = kv
        self.barrier_id = barrier_id
        self.worker_id = worker_id

    async def sync(self, *, timeout: float = 120.0, lease_id: int = 0) -> dict:
        prefix = _barrier_prefix(self.barrier_id)
        watch = self.kv.watch_prefix(prefix + "leader")
        try:
            async with asyncio.timeout(timeout):
                async for event in watch:
                    if event.type == WatchEventType.PUT:
                        data = json.loads(event.entry.value)
                        await self.kv.put(
                            prefix + f"workers/{self.worker_id}", b"ready", lease_id
                        )
                        return data
        except TimeoutError:
            raise TimeoutError(f"barrier {self.barrier_id}: no leader within {timeout}s") from None
        finally:
            watch.cancel()
        raise RuntimeError("unreachable")
