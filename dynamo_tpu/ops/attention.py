"""Attention ops over the paged KV cache.

The KV cache is a flat pool of fixed-size blocks per layer —
``[num_blocks, block_size, kv_heads, head_dim]`` — addressed by per-sequence
block tables (replaces the reference's engine-internal paged KV and its CUDA
block_copy kernel, lib/llm/src/kernels/block_copy.cu, with XLA/Pallas-native
equivalents).  All shapes are static; padding is masked, never branched on.

Pure-JAX implementations here run on CPU test meshes and TPU alike; the
Pallas TPU kernels in ``dynamo_tpu.ops.pallas`` override the hot paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def write_prefill_kv(
    k_cache: jnp.ndarray,   # [num_blocks, block_size, kv_heads, head_dim]
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,     # [seq_pad, kv_heads, head_dim]
    v_new: jnp.ndarray,
    block_ids: jnp.ndarray,  # [max_blocks] int32, padded with any value
    seq_len: jnp.ndarray,    # scalar int32: number of valid tokens
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a prefilled sequence's K/V into its assigned cache blocks."""
    num_blocks, block_size, _, _ = k_cache.shape
    seq_pad = k_new.shape[0]
    token_idx = jnp.arange(seq_pad, dtype=jnp.int32)
    slots = block_ids[token_idx // block_size] * block_size + token_idx % block_size
    # out-of-range sentinel for padding → dropped by scatter mode="drop"
    slots = jnp.where(token_idx < seq_len, slots, num_blocks * block_size)
    flat_k = k_cache.reshape(num_blocks * block_size, *k_cache.shape[2:])
    flat_v = v_cache.reshape(num_blocks * block_size, *v_cache.shape[2:])
    flat_k = flat_k.at[slots].set(k_new.astype(k_cache.dtype), mode="drop")
    flat_v = flat_v.at[slots].set(v_new.astype(v_cache.dtype), mode="drop")
    return flat_k.reshape(k_cache.shape), flat_v.reshape(v_cache.shape)


def write_decode_kv(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,      # [batch, kv_heads, head_dim] — one token per seq
    v_new: jnp.ndarray,
    slot_ids: jnp.ndarray,   # [batch] int32 flat slot (block*block_size+offset);
                             # out-of-range ⇒ dropped (inactive batch lanes)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    num_blocks, block_size, _, _ = k_cache.shape
    flat_k = k_cache.reshape(num_blocks * block_size, *k_cache.shape[2:])
    flat_v = v_cache.reshape(num_blocks * block_size, *v_cache.shape[2:])
    flat_k = flat_k.at[slot_ids].set(k_new.astype(k_cache.dtype), mode="drop")
    flat_v = flat_v.at[slot_ids].set(v_new.astype(v_cache.dtype), mode="drop")
    return flat_k.reshape(k_cache.shape), flat_v.reshape(v_cache.shape)


def _apply_softcap(logits: jnp.ndarray, cap) -> jnp.ndarray:
    """Gemma-2-style logit soft-capping: cap * tanh(logits / cap)."""
    cap = jnp.float32(cap)
    return cap * jnp.tanh(logits / cap)


def _window_mask(causal, pos_diff, window):
    """AND a sliding-window constraint into ``causal``.

    ``window`` may be a static int (always windowed) or a traced int32
    scalar where <= 0 means full attention — what lets a per-layer window
    array thread through a ``lax.scan`` over heterogeneous layers
    (Gemma-2 alternating local/global, qwen2 max_window_layers splits).
    """
    if isinstance(window, (int, float)):
        return causal & (pos_diff < window)
    return causal & ((window <= 0) | (pos_diff < window))


def dense_causal_attention(
    q: jnp.ndarray,  # [batch, seq, heads, head_dim]
    k: jnp.ndarray,  # [batch, seq, kv_heads, head_dim]
    v: jnp.ndarray,
    seq_len: jnp.ndarray | None = None,  # [batch] valid lengths (padding mask)
    *,
    sliding_window=None,   # Mistral-style: attend the last W only; may be
                           # a traced scalar (<=0 = full) — see _window_mask
    logit_softcap: float | None = None,  # Gemma-2 attn soft-capping
    query_scale: float | None = None,    # override 1/sqrt(head_dim)
) -> jnp.ndarray:
    """Causal self-attention for prefill (GQA-aware, fp32 softmax)."""
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    qg = q.reshape(b, s, kvh, groups, d)
    scale = jnp.float32(query_scale) if query_scale is not None else (
        1.0 / jnp.sqrt(jnp.float32(d))
    )
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits * scale
    if logit_softcap is not None:
        logits = _apply_softcap(logits, logit_softcap)
    pos = jnp.arange(s)
    causal = pos[None, :] <= pos[:, None]  # [q, s]
    if sliding_window is not None:
        # each query sees only the last `sliding_window` positions
        causal = _window_mask(causal, pos[:, None] - pos[None, :], sliding_window)
    mask = causal[None, None, None, :, :]
    if seq_len is not None:
        valid = pos[None, :] < seq_len[:, None]  # [b, s]
        mask = mask & valid[:, None, None, None, :]
    logits = jnp.where(mask, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", weights, v.astype(jnp.float32))
    return out.reshape(b, s, h, d).astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,            # [batch, heads, head_dim] — one query per seq
    k_cache: jnp.ndarray,      # [num_blocks, block_size, kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [batch, max_blocks] int32
    context_lens: jnp.ndarray,  # [batch] int32 (0 ⇒ inactive lane)
    *,
    sliding_window=None,  # attend only the last W positions; may be a
                          # traced scalar (<=0 = full) — see _window_mask
    logit_softcap: float | None = None,
    query_scale: float | None = None,
) -> jnp.ndarray:
    """Decode-step attention: gather each sequence's pages and attend.

    Pure-JAX fallback path; the Pallas kernel reads pages from HBM without
    materializing the gather.
    """
    b, h, d = q.shape
    _, block_size, kvh, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    groups = h // kvh

    k = k_cache[block_tables]  # [b, max_blocks, bs, kvh, d]
    v = v_cache[block_tables]
    length = max_blocks * block_size
    k = k.reshape(b, length, kvh, d)
    v = v.reshape(b, length, kvh, d)

    qg = q.reshape(b, kvh, groups, d).astype(jnp.float32)
    scale = jnp.float32(query_scale) if query_scale is not None else (
        1.0 / jnp.sqrt(jnp.float32(d))
    )
    logits = jnp.einsum("bkgd,blkd->bkgl", qg, k.astype(jnp.float32)) * scale
    if logit_softcap is not None:
        logits = _apply_softcap(logits, logit_softcap)
    pos = jnp.arange(length)[None, :]
    valid = pos < context_lens[:, None]  # [b, l]
    if sliding_window is not None:
        # the query sits at position ctx-1; it sees [ctx-W, ctx), i.e.
        # keys whose distance (ctx-1 - pos) is < W
        valid = _window_mask(
            valid, (context_lens[:, None] - 1) - pos, sliding_window
        )
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    # fully-masked (inactive) lanes produce uniform weights; output is junk
    # but those lanes are discarded by the scheduler
    out = jnp.einsum("bkgl,blkd->bkgd", weights, v.astype(jnp.float32))
    return out.reshape(b, h, d).astype(q.dtype)


def paged_window_attention(
    q: jnp.ndarray,            # [batch, w, heads, head_dim] — w queries per seq
    k_cache: jnp.ndarray,      # [num_blocks, block_size, kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [batch, max_blocks] int32
    context_lens: jnp.ndarray,  # [batch] int32: context length INCLUDING the
                                # window's last token (0 ⇒ inactive lane)
    *,
    sliding_window=None,  # attend only the last W positions per query; may
                          # be a traced scalar (<=0 = full) — _window_mask
    logit_softcap: float | None = None,
    query_scale: float | None = None,
) -> jnp.ndarray:
    """Multi-query decode attention for speculative verification: the w
    window tokens' K/V are already written to the cache (like decode), and
    query i attends up to absolute position ``context_lens - w + i``
    (causal within the window, full context before it).  Returns
    [batch, w, heads, head_dim]."""
    b, w, h, d = q.shape
    _, block_size, kvh, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    groups = h // kvh

    k = k_cache[block_tables].reshape(b, max_blocks * block_size, kvh, d)
    v = v_cache[block_tables].reshape(b, max_blocks * block_size, kvh, d)
    length = max_blocks * block_size

    qg = q.reshape(b, w, kvh, groups, d).astype(jnp.float32)
    scale = jnp.float32(query_scale) if query_scale is not None else (
        1.0 / jnp.sqrt(jnp.float32(d))
    )
    logits = jnp.einsum("bwkgd,blkd->bkgwl", qg, k.astype(jnp.float32)) * scale
    if logit_softcap is not None:
        logits = _apply_softcap(logits, logit_softcap)
    # query i sits at absolute position context_lens - w + i; it sees
    # positions <= its own
    q_pos = context_lens[:, None] - w + jnp.arange(w)[None, :]       # [b, w]
    kv_pos = jnp.arange(length)[None, None, :]                        # [1, 1, l]
    mask = kv_pos <= q_pos[:, :, None]                                # [b, w, l]
    if sliding_window is not None:
        mask = _window_mask(mask, q_pos[:, :, None] - kv_pos, sliding_window)
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgwl,blkd->bwkgd", weights, v.astype(jnp.float32))
    return out.reshape(b, w, h, d).astype(q.dtype)


def ragged_paged_attention(
    q: jnp.ndarray,             # [T, heads, head_dim] flat ragged token batch
    k_cache: jnp.ndarray,       # [num_blocks, block_size, kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [lanes, max_blocks] int32
    context_lens: jnp.ndarray,  # [lanes] int32 (unused by the mask: kept for
                                # signature parity with the Pallas kernel)
    token_lane: jnp.ndarray,    # [T] int32 owning lane per token (OOB = pad)
    token_pos: jnp.ndarray,     # [T] int32 absolute position (-1 = pad)
    *,
    sliding_window=None,  # attend only the last W positions per token; may
                          # be a traced scalar (<=0 = full) — _window_mask
    logit_softcap: float | None = None,
    query_scale: float | None = None,
    max_gather_tokens: int = 64,
) -> jnp.ndarray:
    """Ragged unified-batch attention over the paged cache — pure-JAX twin
    of the Pallas kernel (ops/pallas/ragged_attention.py).

    One flat token axis carries chunked-prefill spans and decode tokens from
    different sequences; each token attends its OWN lane's pages at cache
    positions <= its absolute position (causal; every token's K/V — and its
    span predecessors' — must already be written, exactly like the decode
    and verify paths).  Pad tokens (lane OOB / position -1) mask fully and
    produce junk rows the caller discards.

    The per-token page view materializes O(tokens × max_blocks·block_size)
    floats; batches past ``max_gather_tokens`` process in sequential token
    chunks (lax.map) so the working set stays bounded by the chunk — the
    split decode fallback's scale — instead of growing with the window.
    """
    t, h, d = q.shape
    _, block_size, kvh, _ = k_cache.shape
    lanes, max_blocks = block_tables.shape
    groups = h // kvh
    length = max_blocks * block_size

    k = k_cache[block_tables].reshape(lanes, length, kvh, d)
    v = v_cache[block_tables].reshape(lanes, length, kvh, d)
    scale = jnp.float32(query_scale) if query_scale is not None else (
        1.0 / jnp.sqrt(jnp.float32(d))
    )

    def attend(qc, lane_c, pos_c):
        n = qc.shape[0]
        kt = k[lane_c]  # [n, length, kvh, d] — per-token page view
        vt = v[lane_c]
        qg = qc.reshape(n, kvh, groups, d).astype(jnp.float32)
        logits = jnp.einsum(
            "tkgd,tlkd->tkgl", qg, kt.astype(jnp.float32)
        ) * scale
        if logit_softcap is not None:
            logits = _apply_softcap(logits, logit_softcap)
        kv_pos = jnp.arange(length)[None, :]
        # causal per token: pos <= own position (pads at -1 mask everything)
        mask = kv_pos <= pos_c[:, None]
        if sliding_window is not None:
            mask = _window_mask(mask, pos_c[:, None] - kv_pos, sliding_window)
        logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
        weights = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("tkgl,tlkd->tkgd", weights, vt.astype(jnp.float32))
        return out.reshape(n, h, d)

    lane = jnp.clip(token_lane, 0, lanes - 1)
    if t <= max_gather_tokens:
        return attend(q, lane, token_pos).astype(q.dtype)
    ch = max_gather_tokens
    n_chunks = -(-t // ch)
    pad = n_chunks * ch - t
    qp = jnp.pad(q, ((0, pad), (0, 0), (0, 0)))
    lane_p = jnp.pad(lane, (0, pad))                       # lane 0, masked
    pos_p = jnp.pad(token_pos, (0, pad), constant_values=-1)
    out = jax.lax.map(
        lambda a: attend(*a),
        (
            qp.reshape(n_chunks, ch, h, d),
            lane_p.reshape(n_chunks, ch),
            pos_p.reshape(n_chunks, ch),
        ),
    )
    return out.reshape(n_chunks * ch, h, d)[:t].astype(q.dtype)


def ragged_mla_paged_attention(
    q_lat: jnp.ndarray,         # [T, heads, R] absorbed latent queries (f32)
    q_rope: jnp.ndarray,        # [T, heads, P] roped queries
    ck_cache: jnp.ndarray,      # [num_blocks, block_size, R] latent (K AND V)
    kr_cache: jnp.ndarray,      # [num_blocks, block_size, P] rope keys
    block_tables: jnp.ndarray,  # [lanes, max_blocks] int32
    token_lane: jnp.ndarray,    # [T] int32 owning lane per token (OOB = pad)
    token_pos: jnp.ndarray,     # [T] int32 absolute position (-1 = pad)
    *,
    scale: float,
    max_gather_tokens: int = 64,
) -> jnp.ndarray:
    """Ragged unified-batch MLA attention in latent space — pure-JAX twin
    of the Pallas kernel (ops/pallas/mla_attention.py ragged_mla_attention).

    Same contract as ragged_paged_attention but scores are the two-part
    absorbed MLA form (q_lat·c_kv + q_rope·k_rope) and the context is
    accumulated IN latent space [T, heads, R] (float32) for the caller to
    decompress through w_uv.  Token chunking bounds the per-chunk gather
    exactly like the GQA twin."""
    t, h, r = q_lat.shape
    p = q_rope.shape[-1]
    block_size = ck_cache.shape[1]
    lanes, max_blocks = block_tables.shape
    length = max_blocks * block_size

    ck = ck_cache[block_tables].reshape(lanes, length, r)
    kr = kr_cache[block_tables].reshape(lanes, length, p)

    def attend(qlc, qrc, lane_c, pos_c):
        ck_t = ck[lane_c].astype(jnp.float32)  # [n, length, r]
        kr_t = kr[lane_c].astype(jnp.float32)
        logits = (
            jnp.einsum("thr,tlr->thl", qlc.astype(jnp.float32), ck_t)
            + jnp.einsum("thp,tlp->thl", qrc.astype(jnp.float32), kr_t)
        ) * jnp.float32(scale)
        kv_pos = jnp.arange(length)[None, :]
        mask = kv_pos <= pos_c[:, None]  # causal; pads at -1 mask everything
        logits = jnp.where(mask[:, None, :], logits, NEG_INF)
        weights = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("thl,tlr->thr", weights, ck_t)

    lane = jnp.clip(token_lane, 0, lanes - 1)
    if t <= max_gather_tokens:
        return attend(q_lat, q_rope, lane, token_pos)
    ch = max_gather_tokens
    n_chunks = -(-t // ch)
    pad = n_chunks * ch - t
    qlp = jnp.pad(q_lat, ((0, pad), (0, 0), (0, 0)))
    qrp = jnp.pad(q_rope, ((0, pad), (0, 0), (0, 0)))
    lane_p = jnp.pad(lane, (0, pad))                       # lane 0, masked
    pos_p = jnp.pad(token_pos, (0, pad), constant_values=-1)
    out = jax.lax.map(
        lambda a: attend(*a),
        (
            qlp.reshape(n_chunks, ch, h, r),
            qrp.reshape(n_chunks, ch, h, p),
            lane_p.reshape(n_chunks, ch),
            pos_p.reshape(n_chunks, ch),
        ),
    )
    return out.reshape(n_chunks * ch, h, r)[:t]


def window_attention(
    attention: str,
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    sliding_window=None,
    logit_softcap: float | None = None,
    query_scale: float | None = None,
) -> jnp.ndarray:
    """Dispatch speculative-window attention by implementation name
    ("pallas"/"pallas_interpret" → the Pallas window kernel, else the
    XLA gather path above).  One dispatch shared by every family's verify
    forward so kernel signature changes happen in one place.

    ``sliding_window``/``logit_softcap``/``query_scale`` route to the XLA
    path regardless of ``attention``: the Pallas multi-query kernel has
    none of that plumbing yet, and a verify that silently dropped a mask
    or cap would accept drafts the real model would not.
    """
    if (
        attention.startswith("pallas")
        and sliding_window is None
        and logit_softcap is None
        and query_scale is None
    ):
        from dynamo_tpu.ops.pallas import paged_window_attention_decode

        return paged_window_attention_decode(
            q, k_cache, v_cache, block_tables, context_lens,
            interpret=attention == "pallas_interpret",
        )
    return paged_window_attention(
        q, k_cache, v_cache, block_tables, context_lens,
        sliding_window=sliding_window, logit_softcap=logit_softcap,
        query_scale=query_scale,
    )


def position_major_to_batch(t: jnp.ndarray, w: int, b: int, *tail: int) -> jnp.ndarray:
    """Reshape a position-major flat window axis ([w*b, ...], index =
    position*b + lane — the dispatch order that gives position-0 tokens
    expert-capacity priority in MoE verify forwards) into [b, w, ...]."""
    return t.reshape(w, b, *tail).transpose(1, 0, *(i + 2 for i in range(len(tail))))


def gather_prefix_kv(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_ids: jnp.ndarray,  # [max_blocks]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Materialize a sequence's cached K/V (for chunked prefill with reused
    prefix blocks): returns [max_blocks*block_size, kv_heads, head_dim]."""
    k = k_cache[block_ids]
    v = v_cache[block_ids]
    n, bs = k.shape[0], k.shape[1]
    return k.reshape(n * bs, *k.shape[2:]), v.reshape(n * bs, *v.shape[2:])


def prefill_attention_with_prefix(
    q: jnp.ndarray,        # [seq_pad, heads, head_dim]
    k_new: jnp.ndarray,    # [seq_pad, kv_heads, head_dim]
    v_new: jnp.ndarray,
    k_prefix: jnp.ndarray,  # [prefix_pad, kv_heads, head_dim] (gathered pages)
    v_prefix: jnp.ndarray,
    prefix_len: jnp.ndarray,  # scalar: valid prefix tokens
    seq_len: jnp.ndarray,     # scalar: valid new tokens
    *,
    sliding_window=None,  # attend only the last W positions; may be a
                          # traced scalar (<=0 = full) — see _window_mask
    logit_softcap: float | None = None,
    query_scale: float | None = None,
) -> jnp.ndarray:
    """Chunked/continued prefill: queries attend to reused prefix + themselves."""
    s, h, d = q.shape
    kvh = k_new.shape[1]
    groups = h // kvh
    p = k_prefix.shape[0]
    # cast BEFORE concatenating: the prefix comes from the cache (possibly
    # fp8, which jax refuses to promote implicitly), the new K/V from the
    # activation dtype
    k = jnp.concatenate(
        [k_prefix.astype(jnp.float32), k_new.astype(jnp.float32)], axis=0
    )
    v = jnp.concatenate(
        [v_prefix.astype(jnp.float32), v_new.astype(jnp.float32)], axis=0
    )
    qg = q.reshape(s, kvh, groups, d).astype(jnp.float32)
    scale = jnp.float32(query_scale) if query_scale is not None else (
        1.0 / jnp.sqrt(jnp.float32(d))
    )
    logits = jnp.einsum("qkgd,lkd->kgql", qg, k) * scale
    if logit_softcap is not None:
        logits = _apply_softcap(logits, logit_softcap)
    q_pos = prefix_len + jnp.arange(s)
    kv_pos = jnp.arange(p + s)
    kv_valid = (kv_pos < prefix_len) | ((kv_pos >= p) & (kv_pos - p < seq_len))
    # absolute kv position: prefix entries sit at their own index, tail
    # entries at prefix_len + (index - p)
    kv_abs = kv_pos - jnp.where(kv_pos >= p, p - prefix_len, 0)
    causal = kv_abs[None, :] <= q_pos[:, None]
    if sliding_window is not None:
        causal = _window_mask(
            causal, q_pos[:, None] - kv_abs[None, :], sliding_window
        )
    mask = causal & kv_valid[None, :]
    logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("kgql,lkd->qkgd", weights, v)
    return out.reshape(s, h, d).astype(q.dtype)
