"""Kernel autotuner for the ragged packed attention family.

The unified-batch kernels expose a small tunable space that the engine
historically filled with heuristics:

- ``tb_tokens`` — token-block size of the packed ragged kernel (was
  ``gcd(block_size, 8)``);
- ``page_slots`` — static width of the per-token-block page worklist
  (was ``tb_tokens * max_blocks_per_seq``, hugely oversized for decode-
  heavy windows: every step past ``page_count`` is a dead pipeline tick);
- ``pages_per_step`` — KV pages DMA'd per grid step (ragged kernels) /
  pages per compute block (``paged_attention`` / ``mla_attention``).

This module sweeps that space per **(model geometry, device_kind,
dtype)** key.  On CPU the sweep is scored by a deterministic cost model
over the REAL host packer (``pack_page_meta`` builds the worklists for a
synthetic decode-heavy + mixed-chunk workload, so packing waste and
feasibility are exact); on TPU ``scripts/tpu_validate.py --bench`` passes
a wall-clock ``runner`` and the winner is measured, not modeled.  Winners
persist as provenance-stamped rows in ``KERNEL_PERF.json`` (same table
the calibration benches write); the engine resolves them at init with the
precedence **explicit knob > tuned row > heuristic default**.

Row schema (version 1)::

    {"bench": "autotune_ragged", "geometry": "h4kv2d64-bs4-l4-mb16",
     "device_kind": "any" | "<jax device_kind>", "dtype": "float32",
     "source": "cost_model" | "measured", "version": 1,
     "tb_tokens": 4, "page_slots": 16, "pages_per_step": 2,
     "cost": 123.4, "swept": 18}

``source="cost_model"`` rows are hardware-independent layout choices and
are stamped ``device_kind="any"``; ``source="measured"`` rows are only
trusted for the device kind that produced them.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

import numpy as np

RAGGED_BENCH = "autotune_ragged"
SCHEMA_VERSION = 1

# cost-model coefficients (arbitrary units; only ratios matter).  DMA is
# the dominant real cost of decode attention, per-step overhead is the
# pipeline bubble each grid step pays, MAC covers the masked score/row
# waste that grows with tb_tokens, SELECT the per-token routing chain,
# PAD the dead pipeline tick a deduped pad slot still occupies.
_C_DMA = 1.0        # per KV byte streamed
_C_STEP = 4096.0    # per grid step
_C_MAC = 0.002      # per masked MAC in the score matrix
_C_SELECT = 64.0    # per select in the routing chain, per live page
_C_PAD = 256.0      # per dead (pad) worklist slot


@dataclasses.dataclass(frozen=True)
class Geometry:
    """The shape key the tuned parameters depend on: attention geometry,
    cache page size, and the engine's packing envelope (decode lanes and
    worst-case pages per lane)."""

    num_heads: int
    num_kv_heads: int
    head_dim: int
    block_size: int
    lanes: int               # max_batch_size — decode lanes per window
    max_blocks_per_seq: int

    @property
    def key(self) -> str:
        return (
            f"h{self.num_heads}kv{self.num_kv_heads}d{self.head_dim}"
            f"-bs{self.block_size}-l{self.lanes}-mb{self.max_blocks_per_seq}"
        )


def _synthetic_workloads(geom: Geometry, tb: int):
    """Deterministic (token_lane, token_pos, block_tables) workloads the
    cost model scores: a full decode window with every lane mid-stream,
    and a mixed window (decode lanes + one chunked-prefill span).  Both
    are derived purely from the geometry — no RNG, no wall clock."""
    lanes = geom.lanes
    bs = geom.block_size
    mid = max(bs, (geom.max_blocks_per_seq * bs) // 2)
    bt = np.arange(
        lanes * geom.max_blocks_per_seq, dtype=np.int32
    ).reshape(lanes, geom.max_blocks_per_seq)

    def pad_to(arr, fill):
        t_pad = -(-len(arr) // tb) * tb
        out = np.full(t_pad, fill, np.int32)
        out[: len(arr)] = arr
        return out

    # decode-heavy: one token per lane, staggered contexts around mid
    d_lane = np.arange(lanes, dtype=np.int32)
    d_pos = np.array([mid - 1 + (i % bs) for i in range(lanes)], np.int32)
    decode = (pad_to(d_lane, lanes), pad_to(d_pos, -1), bt)

    # mixed: a 2-page prefill chunk on lane 0 + the other lanes decoding
    chunk = 2 * bs
    m_lane = np.concatenate([
        np.zeros(chunk, np.int32), np.arange(1, lanes, dtype=np.int32)
    ])
    m_pos = np.concatenate([
        np.arange(chunk, dtype=np.int32),
        np.array([mid - 1 + (i % bs) for i in range(1, lanes)], np.int32),
    ])
    mixed = (pad_to(m_lane, lanes), pad_to(m_pos, -1), bt)
    return (decode, mixed)


def _pack_stats(geom: Geometry, tb: int):
    """Run the real host packer over the synthetic workloads; return
    (need, per-workload [num_tb, live_pages] pairs).  ``need`` is the
    tightest page_slots width that fits every workload."""
    from dynamo_tpu.ops.pallas.ragged_attention import pack_page_meta

    need = 1
    stats = []
    for token_lane, token_pos, bt in _synthetic_workloads(geom, tb):
        page_phys, _, _, page_count = pack_page_meta(
            token_lane, token_pos, bt,
            tb_tokens=tb, block_size=geom.block_size,
        )
        need = max(need, page_phys.shape[1])
        stats.append((page_phys.shape[0], int(page_count.sum())))
    return need, stats


def cost_model(geom: Geometry, tb: int, ps: int, pps: int,
               dtype_bytes: int = 4) -> float | None:
    """Deterministic score (lower is better) for one candidate; None when
    the candidate cannot hold the synthetic workloads (the engine would
    hit the overflow-repack ladder on typical traffic)."""
    need, stats = _pack_stats(geom, tb)
    if ps < need or ps % pps:
        return None
    page_bytes = (
        2 * geom.block_size * geom.num_kv_heads * geom.head_dim * dtype_bytes
    )
    tbh = tb * geom.num_heads
    score_cols = geom.block_size * geom.num_kv_heads
    cost = 0.0
    for num_tb, live in stats:
        steps = num_tb * (ps // pps)
        cost += _C_STEP * steps
        cost += _C_DMA * live * page_bytes
        cost += _C_MAC * live * tbh * score_cols
        cost += _C_SELECT * live * tb
        cost += _C_PAD * (num_tb * ps - live)
    return cost


def candidate_grid(geom: Geometry, buckets: tuple[int, ...] = ()) -> list[dict]:
    """The swept (tb_tokens, page_slots, pages_per_step) candidates.
    ``buckets`` (the engine's unified token buckets) constrain tb_tokens:
    a tb that does not divide every bucket would force the split
    fallback, so it is never a valid winner."""
    default_tb = math.gcd(geom.block_size, 8) or 1
    tbs = sorted({
        t for t in (1, 2, 4, 8, 16, default_tb)
        if t <= max(geom.lanes, default_tb)
        and all(b % t == 0 for b in buckets)
    })
    out = []
    for tb in tbs:
        need, _ = _pack_stats(geom, tb)
        full = tb * geom.max_blocks_per_seq
        for pps in (1, 2, 4, 8):
            # round the tight width up to a pps multiple; also sweep a
            # 2x-slack width and the legacy full width
            tight = -(-need // pps) * pps
            for ps in sorted({tight, min(full, 2 * tight), full}):
                if ps < need or ps % pps:
                    continue
                out.append(
                    {"tb_tokens": tb, "page_slots": ps, "pages_per_step": pps}
                )
    # dedup, preserving order
    seen = set()
    uniq = []
    for c in out:
        k = (c["tb_tokens"], c["page_slots"], c["pages_per_step"])
        if k not in seen:
            seen.add(k)
            uniq.append(c)
    return uniq


def sweep(
    geom: Geometry,
    *,
    dtype: str = "float32",
    buckets: tuple[int, ...] = (),
    runner=None,
    device_kind: str | None = None,
) -> dict:
    """Score every candidate and return the winner row (plus the swept
    grid under ``"grid"`` for bench reporting).  ``runner`` is an optional
    ``callable(candidate) -> wall_us | None`` — when present the sweep is
    *measured* and stamped with the real device kind; otherwise the
    deterministic cost model scores it (``device_kind="any"``)."""
    dtype_bytes = max(1, np.dtype(dtype).itemsize)
    grid = candidate_grid(geom, buckets)
    if not grid:
        raise ValueError(f"no feasible candidates for {geom.key}")
    scored = []
    for cand in grid:
        if runner is not None:
            cost = runner(dict(cand))
        else:
            cost = cost_model(
                geom, cand["tb_tokens"], cand["page_slots"],
                cand["pages_per_step"], dtype_bytes,
            )
        if cost is None:
            continue
        scored.append((float(cost), cand))
    if not scored:
        raise ValueError(f"no candidate survived the sweep for {geom.key}")
    scored.sort(key=lambda it: (it[0], sorted(it[1].items())))
    best_cost, best = scored[0]
    row = {
        "bench": RAGGED_BENCH,
        "geometry": geom.key,
        "device_kind": device_kind if runner is not None else "any",
        "dtype": str(dtype),
        "source": "measured" if runner is not None else "cost_model",
        "version": SCHEMA_VERSION,
        **best,
        "cost": round(best_cost, 3),
        "swept": len(grid),
    }
    row["grid"] = [
        {**cand, "cost": round(cost, 3)} for cost, cand in scored
    ]
    return row


# ------------------------------------------------------------ persistence


def _row_key(row: dict) -> tuple:
    return (
        row.get("bench"), row.get("geometry"), row.get("device_kind"),
        row.get("dtype"), row.get("source"), row.get("version"),
    )


def load_table(path) -> dict:
    """Read a KERNEL_PERF-format table ({header..., "rows": [...]}) or
    return an empty shell when the file does not exist / fails to parse."""
    try:
        with open(path) as fh:
            table = json.load(fh)
    except (OSError, ValueError):
        return {"rows": []}
    if not isinstance(table, dict):
        return {"rows": []}
    table.setdefault("rows", [])
    return table


def tune(
    path,
    geom: Geometry,
    *,
    dtype: str = "float32",
    buckets: tuple[int, ...] = (),
    runner=None,
    device_kind: str | None = None,
) -> tuple[dict, bool]:
    """Sweep-or-load: return ``(row, cached)``.  An existing row for the
    same (bench, geometry, device_kind, dtype, source, version) key is a
    cache hit — the file is not touched and no sweep runs.  Otherwise the
    winner is upserted into ``path`` (header and unrelated rows are
    preserved)."""
    source = "measured" if runner is not None else "cost_model"
    kind = device_kind if runner is not None else "any"
    probe = {
        "bench": RAGGED_BENCH, "geometry": geom.key, "device_kind": kind,
        "dtype": str(dtype), "source": source, "version": SCHEMA_VERSION,
    }
    table = load_table(path)
    for row in table["rows"]:
        if _row_key(row) == _row_key(probe):
            return row, True
    row = sweep(
        geom, dtype=dtype, buckets=buckets, runner=runner,
        device_kind=device_kind,
    )
    row = {k: v for k, v in row.items() if k != "grid"}
    table["rows"] = [
        r for r in table["rows"] if _row_key(r) != _row_key(row)
    ] + [row]
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(table, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)
    return row, False


def resolve(
    table: dict,
    *,
    geometry_key: str,
    device_kind: str | None,
    dtype: str,
    bench: str = RAGGED_BENCH,
) -> dict | None:
    """Pick the tuned row for a geometry: a measured row for this exact
    device kind wins over the hardware-independent cost-model row; rows
    for other devices, dtypes, or schema versions never match."""
    rows = [
        r for r in table.get("rows", ())
        if r.get("bench") == bench
        and r.get("geometry") == geometry_key
        and r.get("dtype") == str(dtype)
        and r.get("version") == SCHEMA_VERSION
        and all(k in r for k in ("tb_tokens", "page_slots", "pages_per_step"))
    ]
    measured = [
        r for r in rows
        if r.get("source") == "measured"
        and device_kind is not None
        and r.get("device_kind") == device_kind
    ]
    if measured:
        return measured[0]
    modeled = [
        r for r in rows
        if r.get("source") == "cost_model" and r.get("device_kind") == "any"
    ]
    return modeled[0] if modeled else None
