"""Token sampling: vectorized greedy / temperature / top-k / top-p.

All sampling parameters are per-request arrays so one jitted call samples an
entire continuous batch with heterogeneous settings (static shapes, no
per-request branching).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(
    logits: jnp.ndarray,        # [batch, vocab] (any float dtype)
    rng: jax.Array,             # one key [2] (split per lane) or per-lane keys [batch, 2]
    temperature: jnp.ndarray,   # [batch] float32; <=0 treated as greedy
    top_k: jnp.ndarray,         # [batch] int32; <=0 disables
    top_p: jnp.ndarray,         # [batch] float32; >=1 disables
    greedy: jnp.ndarray,        # [batch] bool
) -> jnp.ndarray:
    """Returns sampled token ids [batch] int32.

    Per-lane keys make sampling reproducible per request (OpenAI ``seed``):
    lane i draws only from its own key stream regardless of batch
    composition."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    force_greedy = greedy | (temperature <= 1e-5)
    safe_temp = jnp.where(force_greedy, 1.0, temperature)
    scaled = logits / safe_temp[:, None]

    # sorted-space filtering: one descending sort serves both top-k and top-p
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    sort_idx = jnp.argsort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    ranks = jnp.arange(v)[None, :]

    k_eff = jnp.where(top_k <= 0, v, top_k)[:, None]
    p_eff = jnp.where(top_p >= 1.0, 2.0, top_p)[:, None]
    keep = (ranks < k_eff) & (cum_excl < p_eff)
    keep = keep.at[:, 0].set(True)  # always keep the best token

    filtered_sorted = jnp.where(keep, sorted_logits, NEG_INF)
    # sample in sorted space, map back through sort_idx
    if rng.ndim == 1:
        keys = jax.random.split(rng, b)
    else:
        keys = rng
    choice = jax.vmap(lambda k, lg: jax.random.categorical(k, lg))(keys, filtered_sorted)
    sampled_ids = jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(force_greedy, greedy_ids, sampled_ids)


def apply_penalties(
    logits: jnp.ndarray,            # [batch, vocab]
    gen_counts: jnp.ndarray,        # [batch, vocab] int32: tokens generated so far
    prompt_counts: jnp.ndarray,     # [batch, vocab] int32: prompt token counts
    presence_penalty: jnp.ndarray,  # [batch]
    frequency_penalty: jnp.ndarray,  # [batch]
    repetition_penalty: jnp.ndarray,  # [batch]; 1.0 disables
) -> jnp.ndarray:
    """OpenAI presence/frequency penalties apply to *generated* tokens; the
    HF-style repetition penalty applies to everything seen (prompt +
    generated)."""
    logits = logits.astype(jnp.float32)
    generated = (gen_counts > 0).astype(jnp.float32)
    logits = logits - presence_penalty[:, None] * generated
    logits = logits - frequency_penalty[:, None] * gen_counts.astype(jnp.float32)
    seen = (gen_counts > 0) | (prompt_counts > 0)
    rep = repetition_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen, penalized, logits)
    return logits


def apply_logit_bias(
    logits: jnp.ndarray,  # [batch, vocab] f32
    ids: jnp.ndarray,     # [batch, K] int32; pad entries = vocab (dropped)
    vals: jnp.ndarray,    # [batch, K] f32
) -> jnp.ndarray:
    """OpenAI ``logit_bias``: add per-token biases before sampling.  The
    sparse (ids, vals) rows are fixed-width (engine compile bucket); OOB
    pad ids drop out of the scatter."""
    if ids.shape[-1] == 0:
        return logits
    b = logits.shape[0]
    return logits.at[jnp.arange(b)[:, None], ids].add(vals, mode="drop")


def token_logprobs(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """log-softmax probability of each chosen token [batch] (float32),
    computed from the given logits (the engine passes the penalized,
    untempered distribution — vLLM's convention for reported logprobs)."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, tokens.astype(jnp.int32)[:, None], axis=-1
    )[:, 0]
    return picked - lse


def topk_logprobs(logits: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k log-softmax probabilities and their token ids
    ([batch, k] f32, [batch, k] i32) from the given logits."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    vals, ids = jax.lax.top_k(logits, k)
    return vals - lse, ids.astype(jnp.int32)
