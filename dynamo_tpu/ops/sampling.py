"""Token sampling: vectorized greedy / temperature / top-k / top-p.

All sampling parameters are per-request arrays so one jitted call samples an
entire continuous batch with heterogeneous settings (static shapes, no
per-request branching).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(
    logits: jnp.ndarray,        # [batch, vocab] (any float dtype)
    rng: jax.Array,
    temperature: jnp.ndarray,   # [batch] float32; <=0 treated as greedy
    top_k: jnp.ndarray,         # [batch] int32; <=0 disables
    top_p: jnp.ndarray,         # [batch] float32; >=1 disables
    greedy: jnp.ndarray,        # [batch] bool
) -> jnp.ndarray:
    """Returns sampled token ids [batch] int32."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    force_greedy = greedy | (temperature <= 1e-5)
    safe_temp = jnp.where(force_greedy, 1.0, temperature)
    scaled = logits / safe_temp[:, None]

    # sorted-space filtering: one descending sort serves both top-k and top-p
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    sort_idx = jnp.argsort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    ranks = jnp.arange(v)[None, :]

    k_eff = jnp.where(top_k <= 0, v, top_k)[:, None]
    p_eff = jnp.where(top_p >= 1.0, 2.0, top_p)[:, None]
    keep = (ranks < k_eff) & (cum_excl < p_eff)
    keep = keep.at[:, 0].set(True)  # always keep the best token

    filtered_sorted = jnp.where(keep, sorted_logits, NEG_INF)
    # sample in sorted space, map back through sort_idx
    choice = jax.random.categorical(rng, filtered_sorted, axis=-1)
    sampled_ids = jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(force_greedy, greedy_ids, sampled_ids)


def apply_penalties(
    logits: jnp.ndarray,            # [batch, vocab]
    output_counts: jnp.ndarray,     # [batch, vocab] int32: tokens generated so far
    presence_penalty: jnp.ndarray,  # [batch]
    frequency_penalty: jnp.ndarray,  # [batch]
    repetition_penalty: jnp.ndarray,  # [batch]; 1.0 disables
) -> jnp.ndarray:
    logits = logits.astype(jnp.float32)
    appeared = (output_counts > 0).astype(jnp.float32)
    logits = logits - presence_penalty[:, None] * appeared
    logits = logits - frequency_penalty[:, None] * output_counts.astype(jnp.float32)
    rep = repetition_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(appeared > 0, penalized, logits)
    return logits
