"""TPU compute ops.

Pure-JAX reference implementations (run everywhere, incl. the 8-device CPU
test mesh) + Pallas TPU kernels for the hot paths.  Everything is static-shape
and jit-friendly: no data-dependent Python control flow.
"""

from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rope import apply_rope, rope_table
from dynamo_tpu.ops.attention import (
    dense_causal_attention,
    paged_decode_attention,
    write_prefill_kv,
)
from dynamo_tpu.ops.sampling import sample_tokens

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_table",
    "dense_causal_attention",
    "paged_decode_attention",
    "write_prefill_kv",
    "sample_tokens",
]
