"""Rotary position embeddings.

Split-half convention (llama-family): rotate pairs (x[..., :d/2], x[..., d/2:]).
Tables are precomputed once per model and indexed by absolute position, so
decode steps at arbitrary offsets are a cheap gather.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_table(max_len: int, head_dim: int, theta: float = 10000.0) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute (cos, sin) tables, shape [max_len, head_dim//2], float32."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = jnp.arange(max_len, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray,          # [..., seq, heads, head_dim]
    positions: jnp.ndarray,  # [..., seq]
    cos_table: jnp.ndarray,  # [max_len, head_dim//2]
    sin_table: jnp.ndarray,
) -> jnp.ndarray:
    cos = cos_table[positions][..., None, :]  # [..., seq, 1, half]
    sin = sin_table[positions][..., None, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    rotated = jnp.concatenate([x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1)
    return rotated.astype(x.dtype)
