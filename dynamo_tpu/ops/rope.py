"""Rotary position embeddings.

Split-half convention (llama-family): rotate pairs (x[..., :d/2], x[..., d/2:]).
Tables are precomputed once per model and indexed by absolute position, so
decode steps at arbitrary offsets are a cheap gather.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def _llama3_scale_freqs(freqs: jnp.ndarray, scaling: dict) -> jnp.ndarray:
    """Llama-3.1 frequency-dependent scaling: long wavelengths divide by
    ``factor``, short ones stay, a smooth ramp interpolates between
    (reference semantics: HF modeling_rope_utils _compute_llama3_parameters)."""
    factor = float(scaling.get("factor", 8.0))
    low = float(scaling.get("low_freq_factor", 1.0))
    high = float(scaling.get("high_freq_factor", 4.0))
    orig = float(scaling.get("original_max_position_embeddings", 8192))

    wavelen = 2.0 * math.pi / freqs
    low_wavelen = orig / low
    high_wavelen = orig / high
    smooth = (orig / wavelen - low) / (high - low)
    interp = (1.0 - smooth) * (freqs / factor) + smooth * freqs
    out = jnp.where(wavelen > low_wavelen, freqs / factor, freqs)
    mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
    return jnp.where(mid, interp, out)


def _yarn_scale_freqs(freqs: jnp.ndarray, half: int, theta: float, scaling: dict) -> jnp.ndarray:
    """YaRN NTK-by-parts interpolation (reference semantics: the YaRN paper
    / HF _compute_yarn_parameters; DeepSeek-V2+ long-context rope): dims
    whose rotations at the original context are many (high-frequency)
    extrapolate (keep), few (low-frequency) interpolate (divide by factor),
    with a linear ramp between ``beta_fast`` and ``beta_slow`` rotations."""
    factor = float(scaling.get("factor", 1.0))
    orig = float(scaling.get("original_max_position_embeddings", 4096))
    beta_fast = float(scaling.get("beta_fast", 32.0))
    beta_slow = float(scaling.get("beta_slow", 1.0))

    def dim_for_rotations(rot: float) -> float:
        # dim index whose wavelength fits `rot` rotations in `orig` tokens
        return (2 * half) * math.log(orig / (rot * 2 * math.pi)) / (2 * math.log(theta))

    low = max(math.floor(dim_for_rotations(beta_fast)), 0)
    high = min(math.ceil(dim_for_rotations(beta_slow)), half - 1)
    ramp = jnp.clip(
        (jnp.arange(half, dtype=jnp.float32) - low) / max(high - low, 1e-3), 0.0, 1.0
    )
    extrapolation = freqs            # high-frequency dims keep
    interpolation = freqs / factor   # low-frequency dims stretch
    return interpolation * ramp + extrapolation * (1.0 - ramp)


def yarn_mscale(scaling: dict | None) -> float:
    """YaRN attention-temperature correction: multiply the softmax scale by
    ``mscale**2`` (DeepSeek convention, mscale_all_dim)."""
    if not scaling or scaling.get("rope_type", scaling.get("type")) != "yarn":
        return 1.0
    factor = float(scaling.get("factor", 1.0))
    # HF DeepSeek applies the softmax-scale correction only when
    # mscale_all_dim is nonzero ("mscale" alone affects the reference's
    # cos/sin ratio, not the softmax temperature)
    m_all = float(scaling.get("mscale_all_dim", 0.0) or 0.0)
    if factor <= 1.0 or not m_all:
        return 1.0
    return 0.1 * m_all * math.log(factor) + 1.0


def rope_table(
    max_len: int, head_dim: int, theta: float = 10000.0,
    scaling: dict | None = None,
    *,
    yarn_apply_attention_factor: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute (cos, sin) tables, shape [max_len, head_dim//2], float32.

    ``scaling`` is an HF ``rope_scaling`` dict: type "linear", "llama3"
    (Llama-3.1+) or "yarn".  For yarn, HF's llama-family convention bakes
    the attention temperature (``attention_factor``, default
    0.1*ln(factor)+1) into the tables — both q and k scale by it, squaring
    into the logits.  DeepSeek compensates on the softmax scale instead
    (``yarn_mscale``), so its caller passes
    ``yarn_apply_attention_factor=False``."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    attn_factor = 1.0
    if scaling:
        kind = scaling.get("rope_type", scaling.get("type", ""))
        if kind == "linear":
            freqs = freqs / float(scaling.get("factor", 1.0))
        elif kind == "llama3":
            freqs = _llama3_scale_freqs(freqs, scaling)
        elif kind == "yarn":
            freqs = _yarn_scale_freqs(freqs, half, theta, scaling)
            if yarn_apply_attention_factor:
                factor = float(scaling.get("factor", 1.0))
                attn_factor = float(
                    scaling.get("attention_factor")
                    or (0.1 * math.log(factor) + 1.0 if factor > 1.0 else 1.0)
                )
        elif kind:
            raise NotImplementedError(f"rope_scaling type {kind!r}")
    angles = jnp.arange(max_len, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles) * attn_factor, jnp.sin(angles) * attn_factor


def apply_rope(
    x: jnp.ndarray,          # [..., seq, heads, head_dim]
    positions: jnp.ndarray,  # [..., seq]
    cos_table: jnp.ndarray,  # [max_len, head_dim//2]
    sin_table: jnp.ndarray,
) -> jnp.ndarray:
    cos = cos_table[positions][..., None, :]  # [..., seq, 1, half]
    sin = sin_table[positions][..., None, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    rotated = jnp.concatenate([x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1)
    return rotated.astype(x.dtype)
