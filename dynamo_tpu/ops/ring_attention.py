"""Ring attention: causal attention with the sequence sharded over mesh axis
``sp``.

Long-context prefill beyond one chip's HBM: each device holds a contiguous
sequence chunk of Q/K/V; K/V chunks rotate around the ring via
``jax.lax.ppermute`` (ICI neighbor exchange) while each device accumulates
flash-style online softmax against its local queries.  Compute overlaps the
rotation; memory per device is O(S/n).

The reference has no sequence/context parallelism (SURVEY.md §2.5 marks it
absent) — this is a TPU-native extension enabling prefill of sequences that
exceed single-chip HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _chunk_attention(q, k, v, q_offset, kv_offset, q_valid, kv_valid):
    """Partial (unnormalized) attention of local q against one K/V chunk.

    q: [B, Sq, KVH, G, D] f32; k/v: [B, Sk, KVH, D] f32.
    Returns (m [B,Sq,KVH,G,1], l [B,...,1], acc [B,Sq,KVH,G,D]).
    """
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    s = jnp.einsum("bqkgd,bskd->bqkgs", q, k) * scale
    sq, sk = q.shape[1], k.shape[1]
    q_pos = q_offset + jnp.arange(sq)
    kv_pos = kv_offset + jnp.arange(sk)
    mask = (kv_pos[None, :] <= q_pos[:, None]) & q_valid[:, None] & kv_valid[None, :]
    s = jnp.where(mask[None, :, None, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, -1e29)  # keep fully-masked rows finite
    p = jnp.exp(s - m)
    p = jnp.where(mask[None, :, None, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bqkgs,bskd->bqkgd", p, v)
    return m, l, acc


def _merge(m1, l1, acc1, m2, l2, acc2):
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return m, l1 * a1 + l2 * a2, acc1 * a1 + acc2 * a2


def _ring_body(q, k, v, seq_len, axis_name: str, num_chunks: int, chunk: int):
    """Per-device shard_map body."""
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    my_idx = jax.lax.axis_index(axis_name)

    qf = q.reshape(b, sq, kvh, groups, d).astype(jnp.float32)
    q_offset = my_idx * chunk
    q_valid = q_offset + jnp.arange(sq) < seq_len

    # mark the fresh accumulators as device-varying over the ring axis so the
    # scan carry types line up (shard_map varying-manual-axes tracking)
    m0 = jax.lax.pcast(
        jnp.full((b, sq, kvh, groups, 1), NEG_INF, jnp.float32), (axis_name,), to="varying"
    )
    l0 = jax.lax.pcast(
        jnp.zeros((b, sq, kvh, groups, 1), jnp.float32), (axis_name,), to="varying"
    )
    acc0 = jax.lax.pcast(
        jnp.zeros((b, sq, kvh, groups, d), jnp.float32), (axis_name,), to="varying"
    )
    perm = [(i, (i + 1) % num_chunks) for i in range(num_chunks)]

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        # chunk currently held after i rotations originated at (my - i) mod n
        kv_idx = (my_idx - i) % num_chunks
        kv_offset = kv_idx * chunk
        kv_valid = kv_offset + jnp.arange(k_cur.shape[1]) < seq_len
        mc, lc, accc = _chunk_attention(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            q_offset, kv_offset, q_valid, kv_valid,
        )
        m, l, acc = _merge(m, l, acc, mc, lc, accc)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m, l, acc), None

    (k_fin, v_fin, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(num_chunks)
    )
    out = acc / jnp.maximum(l, 1e-20)
    return out.reshape(b, sq, h, d).astype(q.dtype)


def _ring_body_with_prefix(
    q, k, v, k_prefix, v_prefix, prefix_len, tail_len,
    axis_name: str, num_chunks: int, chunk: int,
):
    """Per-device body: the tail ring PLUS one flash-merged pass over a
    resident prefix (every valid prefix position is visible to every valid
    tail query, so the prefix pass needs no rotation — each shard attends
    the full replicated prefix once and merges it into the online
    softmax)."""
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    my_idx = jax.lax.axis_index(axis_name)

    qf = q.reshape(b, sq, kvh, groups, d).astype(jnp.float32)
    q_offset = my_idx * chunk
    q_valid = q_offset + jnp.arange(sq) < tail_len

    # prefix pass: absolute positions put every valid tail query after
    # every valid prefix position, so the causal mask inside
    # _chunk_attention reduces to the validity masks
    p_valid = jnp.arange(k_prefix.shape[1]) < prefix_len
    # no pcast needed: these derive from the sharded q (and axis_index),
    # so they are already device-varying over the ring axis
    m0, l0, acc0 = _chunk_attention(
        qf,
        k_prefix.astype(jnp.float32),
        v_prefix.astype(jnp.float32),
        q_offset=prefix_len + q_offset,
        kv_offset=0,
        q_valid=q_valid,
        kv_valid=p_valid,
    )
    perm = [(i, (i + 1) % num_chunks) for i in range(num_chunks)]

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        kv_idx = (my_idx - i) % num_chunks
        kv_offset = kv_idx * chunk
        kv_valid = kv_offset + jnp.arange(k_cur.shape[1]) < tail_len
        mc, lc, accc = _chunk_attention(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            q_offset, kv_offset, q_valid, kv_valid,
        )
        m, l, acc = _merge(m, l, acc, mc, lc, accc)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(num_chunks)
    )
    out = acc / jnp.maximum(l, 1e-20)
    return out.reshape(b, sq, h, d).astype(q.dtype)


def ring_attention_with_prefix(
    q: jnp.ndarray,         # [B, S, H, D] tail queries, S divisible by sp
    k: jnp.ndarray,         # [B, S, KVH, D] tail keys
    v: jnp.ndarray,
    k_prefix: jnp.ndarray,  # [B, P, KVH, D] resident prefix (replicated)
    v_prefix: jnp.ndarray,
    prefix_len: jnp.ndarray,  # scalar int32: valid prefix tokens
    tail_len: jnp.ndarray,    # scalar int32: valid tail tokens
    mesh: Mesh,
    *,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Continued-prefill attention under sequence parallelism: the TAIL is
    sharded over ``axis_name`` and runs the usual ring; the resident
    prefix (gathered from the paged cache, replicated — it already fits as
    KV pages) merges into each shard's online softmax in one extra pass.
    This is what lets prefix caching and chunked prefill compose with an
    sp mesh instead of disabling it."""
    num_chunks = mesh.shape[axis_name]
    s = q.shape[1]
    if s % num_chunks:
        raise ValueError(f"sequence {s} not divisible by {axis_name}={num_chunks}")
    chunk = s // num_chunks
    spec = P(None, axis_name, None, None)

    body = functools.partial(
        _ring_body_with_prefix,
        axis_name=axis_name, num_chunks=num_chunks, chunk=chunk,
    )
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(), P(), P(), P()),
        out_specs=spec,
    )
    return fn(q, k, v, k_prefix, v_prefix, prefix_len, tail_len)


def ring_attention(
    q: jnp.ndarray,   # [B, S, H, D], S divisible by sp size
    k: jnp.ndarray,   # [B, S, KVH, D]
    v: jnp.ndarray,
    seq_len: jnp.ndarray,  # scalar int32 valid length (padding mask)
    mesh: Mesh,
    *,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Causal self-attention with sequence sharded over ``axis_name``."""
    num_chunks = mesh.shape[axis_name]
    s = q.shape[1]
    if s % num_chunks:
        raise ValueError(f"sequence {s} not divisible by {axis_name}={num_chunks}")
    chunk = s // num_chunks
    spec = P(None, axis_name, None, None)

    body = functools.partial(
        _ring_body, axis_name=axis_name, num_chunks=num_chunks, chunk=chunk
    )
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
    )
    return fn(q, k, v, seq_len)
