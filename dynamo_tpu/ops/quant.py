"""Weight-only int8 quantization for serving.

The reference's headline benchmark serves an FP8-quantized model
(DeepSeek-R1-Distill-Llama-70B-FP8-dynamic, examples/llm/benchmarks/
README.md:66-105); the TPU-native analog is weight-only int8: the MXU has
no FP8, but an int8 weight resident in HBM halves the bytes each decode
step streams — and decode is HBM-bandwidth-bound — while the convert to
bf16 fuses into the matmul on TPU (no materialized dequantized copy).

Design:
- ``QuantizedMatrix``: a pytree node pairing int8 values with a symmetric
  per-output-channel scale.  The scale keeps the matrix's ndim (size 1 on
  the contraction axis), so a family's existing ``PartitionSpec`` for the
  full-precision matrix applies verbatim to BOTH leaves — quantization
  never changes the sharding story.
- ``mm(x, w)``: matmul that accepts either a plain array or a
  ``QuantizedMatrix``; model forwards call it instead of ``@`` and stay
  quantization-agnostic.
- ``quantize_params`` / ``quantize_specs``: map a param pytree (and its
  spec twin) replacing named leaves; layer-stacked [L, in, out] weights
  quantize per (layer, out-channel) and still slice correctly under
  ``lax.scan`` (both leaves carry the leading L axis).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedMatrix:
    """Symmetric weight-only int8 matrix: ``w ≈ q.astype(f) * s``.

    ``q``: int8, the original weight's shape.
    ``s``: scale, same ndim, size 1 on the contraction (second-to-last)
    axis — broadcastable against the matmul result.
    """

    q: Any
    s: Any

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # reported dtype = compute dtype of the scale
        return self.s.dtype


def quantize_matrix(w: jnp.ndarray, scale_dtype=jnp.float32) -> QuantizedMatrix:
    """Per-output-channel symmetric int8: scale over the contraction axis
    (second-to-last), keepdims so the scale broadcasts in ``mm``."""
    axis = w.ndim - 2
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return QuantizedMatrix(q=q, s=s.astype(scale_dtype))


def dequantize_matrix(w: QuantizedMatrix, dtype=jnp.bfloat16) -> jnp.ndarray:
    return (w.q.astype(jnp.float32) * w.s.astype(jnp.float32)).astype(dtype)


def mm(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for plain or quantized ``w``.

    Quantized path: the int8→bf16 convert sits directly on the dot operand,
    where XLA:TPU fuses it into the matmul (weights stream from HBM as
    int8); the per-channel scale multiplies the [..., out] result."""
    if isinstance(w, QuantizedMatrix):
        out = x @ w.q.astype(x.dtype)
        # scale is [.., 1, out]; drop the kept contraction axis against the
        # result's [..., out]
        return out * jnp.squeeze(w.s, axis=w.s.ndim - 2).astype(x.dtype)
    return x @ w


def qeinsum(subscripts: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """Two-operand einsum whose second operand may be quantized (e.g. the
    MoE expert banks ``ech,ehi->eci``).  Requires the weight's contraction
    axis to be its second-to-last (the ``quantize_matrix`` convention), so
    the keepdims scale broadcasts against the result unchanged."""
    if isinstance(w, QuantizedMatrix):
        return jnp.einsum(subscripts, x, w.q.astype(x.dtype)) * w.s.astype(x.dtype)
    return jnp.einsum(subscripts, x, w)


def _replace_named_leaves(tree: dict, leaf_names: tuple[str, ...], transform):
    """One walker for the params tree and its spec twin: replace leaves
    matched by dict key (anywhere in the tree) via ``transform``; one match
    rule keeps the two trees structurally identical."""

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k in leaf_names and not isinstance(v, dict):
                    out[k] = transform(v)
                else:
                    out[k] = walk(v)
            return out
        return node

    return walk(tree)


def quantize_params(params: dict, leaf_names: tuple[str, ...]) -> dict:
    """Replace named leaves with QuantizedMatrix nodes."""
    return _replace_named_leaves(params, leaf_names, quantize_matrix)


def quantize_specs(specs: dict, leaf_names: tuple[str, ...]) -> dict:
    """Spec-tree twin of ``quantize_params``: the int8 values keep the
    full-precision leaf's PartitionSpec; the scale keeps it too EXCEPT on
    the contraction axis, where its extent is 1 (keepdims) and cannot carry
    a real sharding (row-parallel matrices like wo shard the contraction
    axis over tp)."""
    from jax.sharding import PartitionSpec as P

    def scale_spec(spec):
        entries = list(spec)
        if len(entries) >= 2:
            entries[-2] = None
        return P(*entries)

    return _replace_named_leaves(
        specs, leaf_names, lambda v: QuantizedMatrix(q=v, s=scale_spec(v))
    )


def is_quantized(params: dict) -> bool:
    """True if the tree contains any QuantizedMatrix node."""
    return any(
        isinstance(x, QuantizedMatrix)
        for x in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedMatrix)
        )
    )
