"""Mixture-of-Experts layer ops.

Capacity-based top-k routing with static shapes (XLA-friendly: no ragged
dispatch):

    dispatch  [T, H] → [E, C, H]   (one-hot scatter by expert slot)
    experts   batched einsum over the expert axis (MXU)
    combine   [E, C, H] → [T, H]   weighted by router probabilities

Expert parallelism = sharding the expert axis over mesh axis ``ep``; GSPMD
lowers dispatch/combine into all-to-alls over ICI (SURVEY.md §2.5 expert
parallel — the reference delegates this to DeepEP inside SGLang; here it is
native).  Tokens over capacity are dropped (standard capacity-factor
behavior); capacity is sized to make drops negligible at serving batch sizes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.quant import qeinsum


def moe_router(
    x: jnp.ndarray, w_router: jnp.ndarray, top_k: int,
    norm_topk_prob: bool = True,
):
    """Returns (expert_ids [T, k], probs [T, k]) — softmax routing
    (DeepSeek-V2 / Mixtral style); ``norm_topk_prob=False`` keeps the raw
    softmax weights for the selected experts (some Qwen3-MoE variants)."""
    logits = (x.astype(jnp.float32)) @ w_router.astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_probs, top_ids = jax.lax.top_k(probs, top_k)
    if norm_topk_prob:
        top_probs = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)
    return top_ids.astype(jnp.int32), top_probs


def moe_router_sigmoid_noaux(
    x: jnp.ndarray,
    w_router: jnp.ndarray,
    bias: jnp.ndarray,        # [E] e_score_correction_bias
    top_k: int,
    *,
    n_group: int = 1,
    topk_group: int = 1,
    norm_topk_prob: bool = True,
):
    """DeepSeek-V3/R1 aux-free routing: sigmoid scores, the load-balancing
    bias affects SELECTION only, group-limited top-k (pick the best
    ``topk_group`` of ``n_group`` expert groups by the sum of each group's
    top-2 biased scores, then top-k experts within), combine weights from
    the UNBIASED scores renormalized over the chosen experts.
    (Reference semantics: HF modeling_deepseek noaux_tc / vLLM
    grouped_topk with scoring_func="sigmoid".)"""
    t = x.shape[0]
    e = w_router.shape[-1]
    logits = (x.astype(jnp.float32)) @ w_router.astype(jnp.float32)  # [T, E]
    scores = jax.nn.sigmoid(logits)
    biased = scores + bias.astype(jnp.float32)[None, :]

    if n_group > 1:
        grouped = biased.reshape(t, n_group, e // n_group)
        top2 = jax.lax.top_k(grouped, min(2, e // n_group))[0]
        group_scores = jnp.sum(top2, axis=-1)                    # [T, G]
        _, keep_groups = jax.lax.top_k(group_scores, topk_group)  # [T, g]
        group_mask = jnp.zeros((t, n_group), jnp.float32).at[
            jnp.arange(t)[:, None], keep_groups
        ].set(1.0)
        expert_mask = jnp.repeat(group_mask, e // n_group, axis=-1)
        biased = jnp.where(expert_mask > 0, biased, -jnp.inf)

    _, top_ids = jax.lax.top_k(biased, top_k)
    top_scores = jnp.take_along_axis(scores, top_ids, axis=-1)
    if norm_topk_prob:
        top_scores = top_scores / (
            jnp.sum(top_scores, axis=-1, keepdims=True) + 1e-20
        )
    return top_ids.astype(jnp.int32), top_scores


def moe_dispatch_combine(
    x: jnp.ndarray,          # [T, H]
    expert_ids: jnp.ndarray,  # [T, k]
    probs: jnp.ndarray,       # [T, k] f32
    w_gate: jnp.ndarray,      # [E, H, I]
    w_up: jnp.ndarray,        # [E, H, I]
    w_down: jnp.ndarray,      # [E, I, H]
    *,
    capacity: int,
) -> jnp.ndarray:
    t, h = x.shape
    e = w_gate.shape[0]
    k = expert_ids.shape[1]

    flat_ids = expert_ids.reshape(-1)                      # [T*k]
    onehot = jax.nn.one_hot(flat_ids, e, dtype=jnp.int32)  # [T*k, E]
    # slot of each (token, k) within its expert's buffer
    slot = jnp.cumsum(onehot, axis=0) * onehot             # [T*k, E]
    slots = jnp.max(slot, axis=-1) - 1                     # [T*k] position, -1 invalid
    within_capacity = (slots >= 0) & (slots < capacity)

    # scatter tokens into [E, C, H]
    buffers = jnp.zeros((e, capacity, h), x.dtype)
    token_idx = jnp.repeat(jnp.arange(t), k)
    safe_expert = jnp.where(within_capacity, flat_ids, 0)
    safe_slot = jnp.where(within_capacity, slots, capacity)  # OOB → dropped
    buffers = buffers.at[safe_expert, safe_slot].set(
        x[token_idx], mode="drop"
    )

    # expert FFN batched over E (rides the MXU per expert shard; qeinsum
    # streams int8-quantized expert banks from HBM — the dominant bytes of
    # an MoE decode step)
    hidden = jax.nn.silu(qeinsum("ech,ehi->eci", buffers, w_gate)) * qeinsum(
        "ech,ehi->eci", buffers, w_up
    )
    out_buffers = qeinsum("eci,eih->ech", hidden, w_down)  # [E, C, H]

    # combine: gather each (token, k)'s expert output, weight by prob
    gathered = out_buffers[safe_expert, safe_slot]            # [T*k, H]
    weights = jnp.where(within_capacity, probs.reshape(-1), 0.0)
    weighted = gathered.astype(jnp.float32) * weights[:, None]
    combined = jnp.zeros((t, h), jnp.float32).at[token_idx].add(weighted)
    return combined.astype(x.dtype)


def moe_ffn(
    x: jnp.ndarray,
    w_router: jnp.ndarray,
    w_gate: jnp.ndarray,
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
    *,
    top_k: int,
    capacity_factor: float = 2.0,
    router_bias: jnp.ndarray | None = None,
    scoring: str = "softmax",     # "softmax" | "sigmoid_noaux"
    n_group: int = 1,
    topk_group: int = 1,
    norm_topk_prob: bool = True,
) -> jnp.ndarray:
    t = x.shape[0]
    e = w_gate.shape[0]
    capacity = max(1, int(t * top_k / e * capacity_factor))
    if scoring == "sigmoid_noaux":
        ids, probs = moe_router_sigmoid_noaux(
            x, w_router,
            router_bias if router_bias is not None else jnp.zeros((e,), jnp.float32),
            top_k, n_group=n_group, topk_group=topk_group,
            norm_topk_prob=norm_topk_prob,
        )
    else:
        ids, probs = moe_router(x, w_router, top_k, norm_topk_prob=norm_topk_prob)
    return moe_dispatch_combine(
        x, ids, probs, w_gate, w_up, w_down, capacity=capacity
    )
