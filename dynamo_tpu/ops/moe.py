"""Mixture-of-Experts layer ops.

Capacity-based top-k routing with static shapes (XLA-friendly: no ragged
dispatch):

    dispatch  [T, H] → [E, C, H]   (one-hot scatter by expert slot)
    experts   batched einsum over the expert axis (MXU)
    combine   [E, C, H] → [T, H]   weighted by router probabilities

Expert parallelism = sharding the expert axis over mesh axis ``ep``; GSPMD
lowers dispatch/combine into all-to-alls over ICI (SURVEY.md §2.5 expert
parallel — the reference delegates this to DeepEP inside SGLang; here it is
native).  Tokens over capacity are dropped (standard capacity-factor
behavior); capacity is sized to make drops negligible at serving batch sizes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_router(x: jnp.ndarray, w_router: jnp.ndarray, top_k: int):
    """Returns (expert_ids [T, k], probs [T, k]) with renormalized top-k."""
    logits = (x.astype(jnp.float32)) @ w_router.astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_probs, top_ids = jax.lax.top_k(probs, top_k)
    top_probs = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)
    return top_ids.astype(jnp.int32), top_probs


def moe_dispatch_combine(
    x: jnp.ndarray,          # [T, H]
    expert_ids: jnp.ndarray,  # [T, k]
    probs: jnp.ndarray,       # [T, k] f32
    w_gate: jnp.ndarray,      # [E, H, I]
    w_up: jnp.ndarray,        # [E, H, I]
    w_down: jnp.ndarray,      # [E, I, H]
    *,
    capacity: int,
) -> jnp.ndarray:
    t, h = x.shape
    e = w_gate.shape[0]
    k = expert_ids.shape[1]

    flat_ids = expert_ids.reshape(-1)                      # [T*k]
    onehot = jax.nn.one_hot(flat_ids, e, dtype=jnp.int32)  # [T*k, E]
    # slot of each (token, k) within its expert's buffer
    slot = jnp.cumsum(onehot, axis=0) * onehot             # [T*k, E]
    slots = jnp.max(slot, axis=-1) - 1                     # [T*k] position, -1 invalid
    within_capacity = (slots >= 0) & (slots < capacity)

    # scatter tokens into [E, C, H]
    buffers = jnp.zeros((e, capacity, h), x.dtype)
    token_idx = jnp.repeat(jnp.arange(t), k)
    safe_expert = jnp.where(within_capacity, flat_ids, 0)
    safe_slot = jnp.where(within_capacity, slots, capacity)  # OOB → dropped
    buffers = buffers.at[safe_expert, safe_slot].set(
        x[token_idx], mode="drop"
    )

    # expert FFN batched over E (rides the MXU per expert shard)
    hidden = jax.nn.silu(jnp.einsum("ech,ehi->eci", buffers, w_gate)) * jnp.einsum(
        "ech,ehi->eci", buffers, w_up
    )
    out_buffers = jnp.einsum("eci,eih->ech", hidden, w_down)  # [E, C, H]

    # combine: gather each (token, k)'s expert output, weight by prob
    gathered = out_buffers[safe_expert, safe_slot]            # [T*k, H]
    weights = jnp.where(within_capacity, probs.reshape(-1), 0.0)
    weighted = gathered.astype(jnp.float32) * weights[:, None]
    combined = jnp.zeros((t, h), jnp.float32).at[token_idx].add(weighted)
    return combined.astype(x.dtype)


def moe_ffn(
    x: jnp.ndarray,
    w_router: jnp.ndarray,
    w_gate: jnp.ndarray,
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
    *,
    top_k: int,
    capacity_factor: float = 2.0,
) -> jnp.ndarray:
    t = x.shape[0]
    e = w_gate.shape[0]
    capacity = max(1, int(t * top_k / e * capacity_factor))
    ids, probs = moe_router(x, w_router, top_k)
    return moe_dispatch_combine(
        x, ids, probs, w_gate, w_up, w_down, capacity=capacity
    )
