"""Normalization ops."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm with float32 accumulation, cast back to input dtype (standard
    llama-family numerics: normalize in fp32 even for bf16 activations)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 / jnp.sqrt(variance + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
