"""Normalization ops."""

from __future__ import annotations

import jax.numpy as jnp


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-6
) -> jnp.ndarray:
    """LayerNorm with float32 accumulation (ViT-family numerics)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    normed = (x32 - mean) / jnp.sqrt(var + eps)
    return (normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm with float32 accumulation, cast back to input dtype (standard
    llama-family numerics: normalize in fp32 even for bf16 activations)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 / jnp.sqrt(variance + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
