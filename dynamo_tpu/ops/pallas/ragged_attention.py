"""Ragged unified-batch paged attention — Pallas TPU kernel.

One launch consumes a RAGGED token batch: chunked-prefill spans and single
decode tokens from different sequences, flattened onto one token axis with
each token at its own absolute position (Ragged Paged Attention,
arxiv 2604.15464).  This is the kernel that lets the engine run mixed
prefill+decode as ONE dispatch — no separate prefill program, no
overlap-pipeline drain at sequence admission.

Layout (follows the page-mapping idiom of ``paged_attention.py``):

- the flat token axis is cut into fixed-size TOKEN BLOCKS of ``tb_tokens``
  rows; the host packs each sequence's query span into whole token blocks
  (a span never shares a block with another sequence), so every grid step
  serves exactly one lane — ``tb_lane[t]`` names it;
- grid = (token blocks × KV pages): for token block ``t`` and page ``p``
  the BlockSpec index_map reads the scalar-prefetched block table row of
  ``tb_lane[t]``, so the page "gather" is pure DMA addressing;
- per-lane row metadata rides in scalar prefetch: ``lane_qstart`` (flat
  index of the span's first token), ``lane_qlen`` (span length, 0 = lane
  hole), ``lane_start`` (absolute position of the span's first token) and
  ``context_lens`` (absolute context INCLUDING the span's last token);
- heads fold into the row axis like the window kernel (row = token*H + h)
  and GQA matching uses iota masks on the [TB*H, bs*KVH] score matrix;
- softmax accumulates online flash-style in VMEM scratch across a token
  block's pages; causality is per-row: token at absolute position q sees
  cache positions <= q, which also masks every other lane's pages because
  pages stream per-lane via the block table.

Padding rows (decode blocks carry 1 live row, span tails round up, the
token axis pads to a compile bucket with ``tb_lane = 0``) mask out through
``lane_qstart``/``lane_qlen`` — their output rows are garbage the caller
never reads.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _ragged_kernel(
    block_tables_ref,   # [lanes, maxb] int32
    context_lens_ref,   # [lanes] int32 — INCLUDING each lane's span end
    tb_lane_ref,        # [num_tb] int32 — lane served by each token block
    lane_qstart_ref,    # [lanes] int32 — flat index of the span's first token
    lane_qlen_ref,      # [lanes] int32 — span length (0 = hole)
    lane_start_ref,     # [lanes] int32 — absolute position of the first token
    q_ref,              # [1, TB*H, D]   (token-major fold: row = tok*H + h)
    k_page_ref,         # [1, bs*KVH, D]
    v_page_ref,
    out_ref,            # [1, TB*H, D]
    m_ref,              # [TB*H, 128] f32
    l_ref,
    acc_ref,            # [TB*H, D] f32
    *,
    block_size: int,
    num_kv_heads: int,
    groups: int,
    head_dim: int,
    max_blocks: int,
    tb_tokens: int,
    sliding_window: int | None,
):
    """Online-softmax page loop for one ragged token block."""
    t = pl.program_id(0)
    page = pl.program_id(1)
    lane = tb_lane_ref[t]
    ctx = context_lens_ref[lane]
    qs = lane_qstart_ref[lane]
    ql = lane_qlen_ref[lane]
    sp = lane_start_ref[lane]
    rows = block_size * num_kv_heads
    h_all = num_kv_heads * groups
    tbh = tb_tokens * h_all

    @pl.when(page == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    page_start = page * block_size

    active = page_start < ctx
    if sliding_window is not None:
        # pages entirely below the OLDEST query's window contribute nothing
        # (lowest visible absolute position = lane_start - (W_s - 1))
        active &= page_start + block_size > sp - (sliding_window - 1)

    @pl.when(active)
    def _compute():
        q = q_ref[0].astype(jnp.float32)        # [TB*H, D]
        k = k_page_ref[0].astype(jnp.float32)   # [bs*KVH, D]
        v = v_page_ref[0].astype(jnp.float32)
        scale = 1.0 / (head_dim ** 0.5)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [TB*H, bs*KVH]
        col = jax.lax.broadcasted_iota(jnp.int32, (1, rows), 1)
        pos = page_start + col // num_kv_heads
        kv_of_col = col % num_kv_heads
        row = jax.lax.broadcasted_iota(jnp.int32, (tbh, 1), 0)
        kv_of_row = (row % h_all) // groups
        # row r serves flat token t*TB + r//H; its offset inside the span
        # places it at absolute position lane_start + offset
        q_rel = t * tb_tokens + row // h_all - qs        # [TB*H, 1]
        q_pos = sp + q_rel
        row_ok = (q_rel >= 0) & (q_rel < ql)
        mask = (kv_of_col == kv_of_row) & row_ok & (pos <= q_pos)
        if sliding_window is not None:
            mask = mask & (pos > q_pos - sliding_window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(page == max_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("tb_tokens", "interpret", "sliding_window")
)
def ragged_paged_attention(
    q: jnp.ndarray,             # [T, H, D] flat ragged token batch
    k_cache: jnp.ndarray,       # [N, bs, KVH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [lanes, maxb] int32
    context_lens: jnp.ndarray,  # [lanes] int32 incl. each span's last token
    tb_lane: jnp.ndarray,       # [T // tb_tokens] int32
    lane_qstart: jnp.ndarray,   # [lanes] int32
    lane_qlen: jnp.ndarray,     # [lanes] int32 (0 = lane hole)
    lane_start: jnp.ndarray,    # [lanes] int32
    *,
    tb_tokens: int = 8,
    interpret: bool = False,
    sliding_window: int | None = None,
) -> jnp.ndarray:
    """Pallas ragged paged attention: causally-masked paged attention over
    one mixed prefill+decode token batch in a single launch (pure-JAX twin:
    ops/attention.py ragged_paged_attention)."""
    t_pad, h, d = q.shape
    n, bs, kvh, _ = k_cache.shape
    maxb = block_tables.shape[1]
    groups = h // kvh
    rows = bs * kvh
    if t_pad % tb_tokens:
        raise ValueError(
            f"flat token axis ({t_pad}) must pack whole token blocks of "
            f"{tb_tokens}"
        )
    num_tb = t_pad // tb_tokens
    tbh = tb_tokens * h

    def kv_map(t, p, bt, cl, tl, qs, ql, ls):
        return (bt[tl[t], p], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(num_tb, maxb),
        in_specs=[
            pl.BlockSpec((1, tbh, d), lambda t, p, *_: (t, 0, 0)),
            pl.BlockSpec((1, rows, d), kv_map),
            pl.BlockSpec((1, rows, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, tbh, d), lambda t, p, *_: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tbh, 128), jnp.float32),
            pltpu.VMEM((tbh, 128), jnp.float32),
            pltpu.VMEM((tbh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel,
        block_size=bs,
        num_kv_heads=kvh,
        groups=groups,
        head_dim=d,
        max_blocks=maxb,
        tb_tokens=tb_tokens,
        sliding_window=sliding_window,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_tb, tbh, d), q.dtype),
        interpret=interpret,
    )(
        block_tables, context_lens, tb_lane, lane_qstart, lane_qlen,
        lane_start,
        q.reshape(num_tb, tbh, d),
        k_cache.reshape(n, rows, d),
        v_cache.reshape(n, rows, d),
    )
    return out.reshape(t_pad, h, d)
