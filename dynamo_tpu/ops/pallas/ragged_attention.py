"""Ragged unified-batch paged attention — Pallas TPU kernel.

One launch consumes a RAGGED token batch: chunked-prefill spans and single
decode tokens from different sequences, flattened onto one token axis with
each token at its own absolute position (Ragged Paged Attention,
arxiv 2604.15464).  This is the kernel that lets the engine run mixed
prefill+decode as ONE dispatch — no separate prefill program, no
overlap-pipeline drain at sequence admission.

Layout (PACKED lanes — multiple sequences share one token block):

- the flat token axis is cut into fixed-size TOKEN BLOCKS of ``tb_tokens``
  rows; the host packs spans AND single decode tokens densely, so one
  block can carry up to ``tb_tokens`` different lanes (a 16-lane
  decode-heavy window fills 2 blocks of 8 instead of burning 16
  one-live-row blocks);
- per-token routing rides in scalar prefetch: ``token_lane[i]`` names
  token i's sequence lane and ``token_pos[i]`` its absolute position
  (-1 = padding row, fully masked) — the same metadata the XLA twin
  consumes, replacing the old one-lane-per-block ``tb_lane`` routing;
- the KV side is a host-flattened page worklist per token block:
  ``page_phys[t, j]`` is the PHYSICAL cache page the grid step (t, j)
  DMAs (the BlockSpec index map reads it directly — no block-table
  indirection in the kernel), ``page_lane[t, j]`` the lane that owns it,
  ``page_ord[t, j]`` its ordinal in that lane's sequence (kv positions
  start at ``ord * block_size``), and ``page_count[t]`` the number of
  live entries.  Pad entries REPEAT the last live physical page so the
  unchanged index map skips their DMA; their compute is gated off by
  ``j < page_count[t]`` (repeating without the gate would double-count
  that page in the softmax accumulator);
- grid = (token blocks × page slots / pages_per_step): page slots is the
  static width of the worklist — a compile-bucket choice of the caller
  (the engine uses one fixed width so there is exactly one unified
  program per token bucket); ``pages_per_step`` folds that many
  consecutive worklist slots into one grid step (each slot gets its own
  input stream + index map, so the DMAs still address single pages);
- heads fold into the row axis like the window kernel (row = token*H + h)
  and GQA matching uses iota masks on the [TB*H, bs*KVH] score matrix;
- softmax accumulates online flash-style in VMEM scratch across a token
  block's page slots; masking is per-row: a row participates in a page
  step iff its token's lane owns the page and the page position is
  causally visible (pos <= token_pos), which also confines every lane to
  its own pages.

Padding rows (position -1 / out-of-range lane) match no page and no
position — their l stays 0, the clamped denominator makes their output
rows zero, and the caller never reads them.

``pack_page_meta`` (plain numpy, host side) builds the page worklist from
the per-token metadata + block tables; the engine and the tests share it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def pack_page_meta(
    token_lane,     # [T] int — owning lane per token (OOB / pos<0 = pad)
    token_pos,      # [T] int — absolute position per token (-1 = pad)
    block_tables,   # [lanes, max_blocks] int — logical->physical pages
    *,
    tb_tokens: int,
    block_size: int,
    page_slots: int | None = None,
    sliding_window: int | None = None,
):
    """Host-side (numpy) page worklist for the packed ragged kernel.

    For every token block: the lanes present in it (first-appearance
    order), then for each lane every page holding kv positions its tokens
    can see — causally up to ``max(token_pos) // block_size`` and, under a
    sliding window, down from ``(min(token_pos) - W + 1) // block_size``.
    Returns ``(page_phys, page_lane, page_ord, page_count)`` int32 arrays
    of width ``page_slots`` (default: the tightest width that fits; the
    engine passes its fixed compile-bucket width).  Pad entries repeat the
    last live physical page so their DMA is skipped by the unchanged
    BlockSpec index; blocks with no live tokens point at page 0 with
    count 0."""
    token_lane = np.asarray(token_lane)
    token_pos = np.asarray(token_pos)
    bt = np.asarray(block_tables)
    lanes = bt.shape[0]
    t_pad = token_lane.shape[0]
    if t_pad % tb_tokens:
        raise ValueError(
            f"flat token axis ({t_pad}) must pack whole token blocks of "
            f"{tb_tokens}"
        )
    num_tb = t_pad // tb_tokens
    per_block: list[list[tuple[int, int, int]]] = []
    for t in range(num_tb):
        span: dict[int, tuple[int, int]] = {}
        for i in range(t * tb_tokens, (t + 1) * tb_tokens):
            lane, pos = int(token_lane[i]), int(token_pos[i])
            if pos < 0 or not 0 <= lane < lanes:
                continue
            lo, hi = span.get(lane, (pos, pos))
            span[lane] = (min(lo, pos), max(hi, pos))
        entries: list[tuple[int, int, int]] = []
        for lane, (lo, hi) in span.items():
            first = 0
            if sliding_window is not None:
                first = max(0, lo - (sliding_window - 1)) // block_size
            for ord_ in range(first, hi // block_size + 1):
                entries.append((int(bt[lane, ord_]), lane, ord_))
        per_block.append(entries)
    need = max((len(e) for e in per_block), default=0)
    ps = page_slots if page_slots is not None else max(1, need)
    if need > ps:
        raise ValueError(
            f"page worklist needs {need} slots but page_slots={ps}"
        )
    page_phys = np.zeros((num_tb, ps), np.int32)
    page_lane = np.full((num_tb, ps), -1, np.int32)
    page_ord = np.zeros((num_tb, ps), np.int32)
    page_count = np.zeros((num_tb,), np.int32)
    for t, entries in enumerate(per_block):
        page_count[t] = len(entries)
        for j, (phys, lane, ord_) in enumerate(entries):
            page_phys[t, j] = phys
            page_lane[t, j] = lane
            page_ord[t, j] = ord_
        if entries:
            page_phys[t, len(entries):] = entries[-1][0]
    return page_phys, page_lane, page_ord, page_count


def _ragged_kernel(
    token_lane_ref,     # [T] int32 — owning lane per token (OOB = pad)
    token_pos_ref,      # [T] int32 — absolute position per token (-1 = pad)
    page_phys_ref,      # [num_tb, PS] int32 — physical page per grid step
    page_lane_ref,      # [num_tb, PS] int32 — lane owning that page
    page_ord_ref,       # [num_tb, PS] int32 — page ordinal in its lane
    page_count_ref,     # [num_tb] int32 — live worklist entries
    q_ref,              # [1, TB*H, D]   (token-major fold: row = tok*H + h)
    *refs,              # pps × (k_page [1, bs*KVH, D], v_page), out, scratch
    block_size: int,
    num_kv_heads: int,
    groups: int,
    head_dim: int,
    page_slots: int,
    tb_tokens: int,
    pages_per_step: int,
    sliding_window: int | None,
):
    """Online-softmax page-worklist loop for one packed token block.

    Each grid step owns ``pages_per_step`` consecutive worklist slots: the
    same cache array is passed once per slot with its own BlockSpec index
    map (index maps address exactly one block, so batching arbitrary
    physical pages into one DMA is impossible — multiple inputs is the
    Pallas way to widen a step), and the kernel folds the slots into the
    running softmax sequentially."""
    pps = pages_per_step
    kv_refs = refs[: 2 * pps]
    out_ref = refs[2 * pps]
    m_ref, l_ref, acc_ref = refs[2 * pps + 1:]
    t = pl.program_id(0)
    j = pl.program_id(1)
    rows = block_size * num_kv_heads
    h_all = num_kv_heads * groups
    tbh = tb_tokens * h_all

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for i in range(pps):
        slot = j * pps + i
        page_lane = page_lane_ref[t, slot]
        page_start = page_ord_ref[t, slot] * block_size
        k_page_ref = kv_refs[2 * i]
        v_page_ref = kv_refs[2 * i + 1]

        @pl.when(slot < page_count_ref[t])
        def _compute(
            k_page_ref=k_page_ref, v_page_ref=v_page_ref,
            page_lane=page_lane, page_start=page_start,
        ):
            q = q_ref[0].astype(jnp.float32)        # [TB*H, D]
            k = k_page_ref[0].astype(jnp.float32)   # [bs*KVH, D]
            v = v_page_ref[0].astype(jnp.float32)
            scale = 1.0 / (head_dim ** 0.5)
            s = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                    # [TB*H, bs*KVH]
            col = jax.lax.broadcasted_iota(jnp.int32, (1, rows), 1)
            pos = page_start + col // num_kv_heads
            kv_of_col = col % num_kv_heads
            row = jax.lax.broadcasted_iota(jnp.int32, (tbh, 1), 0)
            kv_of_row = (row % h_all) // groups
            # per-row routing: row r serves flat token t*TB + r//H — its
            # lane and absolute position come from the scalar-prefetched
            # per-token metadata, folded in as a select chain over the
            # block's tokens (scalar reads broadcast against the row iota;
            # no vector gather)
            tok_of_row = row // h_all
            base = t * tb_tokens
            q_pos = jnp.full((tbh, 1), -1, jnp.int32)
            row_lane = jnp.full((tbh, 1), -1, jnp.int32)
            for rr in range(tb_tokens):
                q_pos = jnp.where(
                    tok_of_row == rr, token_pos_ref[base + rr], q_pos
                )
                row_lane = jnp.where(
                    tok_of_row == rr, token_lane_ref[base + rr], row_lane
                )
            # a row participates iff its token's lane owns this page and
            # the page position is causally visible (pads sit at
            # q_pos = -1 and match nothing; stale slots past a lane's
            # context exceed every q_pos of that lane, so causality masks
            # them too)
            mask = (
                (kv_of_col == kv_of_row)
                & (row_lane == page_lane)
                & (pos <= q_pos)
            )
            if sliding_window is not None:
                mask = mask & (pos > q_pos - sliding_window)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = m_ref[:, :1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p, v,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[...] = acc_ref[...] * alpha + pv
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == page_slots // pps - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "tb_tokens", "pages_per_step", "interpret", "sliding_window"
    ),
)
def ragged_paged_attention(
    q: jnp.ndarray,             # [T, H, D] flat ragged token batch
    k_cache: jnp.ndarray,       # [N, bs, KVH, D]
    v_cache: jnp.ndarray,
    token_lane: jnp.ndarray,    # [T] int32 owning lane (OOB = pad)
    token_pos: jnp.ndarray,     # [T] int32 absolute position (-1 = pad)
    page_phys: jnp.ndarray,     # [T // tb_tokens, PS] int32 (pack_page_meta)
    page_lane: jnp.ndarray,     # [T // tb_tokens, PS] int32
    page_ord: jnp.ndarray,      # [T // tb_tokens, PS] int32
    page_count: jnp.ndarray,    # [T // tb_tokens] int32
    *,
    tb_tokens: int = 8,
    pages_per_step: int = 1,
    interpret: bool = False,
    sliding_window: int | None = None,
) -> jnp.ndarray:
    """Pallas ragged paged attention with PACKED decode lanes: causally
    masked paged attention over one mixed prefill+decode token batch in a
    single launch, multiple lanes per token block (pure-JAX twin:
    ops/attention.py ragged_paged_attention; host metadata builder:
    pack_page_meta).  ``pages_per_step`` widens each grid step to DMA that
    many worklist pages (autotuned; ``page_slots`` must divide evenly)."""
    t_pad, h, d = q.shape
    n, bs, kvh, _ = k_cache.shape
    groups = h // kvh
    rows = bs * kvh
    if t_pad % tb_tokens:
        raise ValueError(
            f"flat token axis ({t_pad}) must pack whole token blocks of "
            f"{tb_tokens}"
        )
    num_tb = t_pad // tb_tokens
    page_slots = page_phys.shape[1]
    pps = pages_per_step
    if pps < 1 or page_slots % pps:
        raise ValueError(
            f"page_slots ({page_slots}) must be a positive multiple of "
            f"pages_per_step ({pps})"
        )
    tbh = tb_tokens * h

    def kv_map_at(i):
        def kv_map(t, j, tl, tp, pp, pln, po, pc):
            return (pp[t, j * pps + i], 0, 0)
        return kv_map

    kv_specs = []
    for i in range(pps):
        m = kv_map_at(i)
        kv_specs += [
            pl.BlockSpec((1, rows, d), m),
            pl.BlockSpec((1, rows, d), m),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(num_tb, page_slots // pps),
        in_specs=[
            pl.BlockSpec((1, tbh, d), lambda t, j, *_: (t, 0, 0)),
            *kv_specs,
        ],
        out_specs=pl.BlockSpec((1, tbh, d), lambda t, j, *_: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tbh, 128), jnp.float32),
            pltpu.VMEM((tbh, 128), jnp.float32),
            pltpu.VMEM((tbh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel,
        block_size=bs,
        num_kv_heads=kvh,
        groups=groups,
        head_dim=d,
        page_slots=page_slots,
        tb_tokens=tb_tokens,
        pages_per_step=pps,
        sliding_window=sliding_window,
    )
    k_flat = k_cache.reshape(n, rows, d)
    v_flat = v_cache.reshape(n, rows, d)
    kv_args = []
    for _ in range(pps):
        kv_args += [k_flat, v_flat]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_tb, tbh, d), q.dtype),
        interpret=interpret,
    )(
        token_lane, token_pos, page_phys, page_lane, page_ord, page_count,
        q.reshape(num_tb, tbh, d),
        *kv_args,
    )
    return out.reshape(t_pad, h, d)
