"""MLA (multi-head latent attention) paged decode — Pallas TPU kernel.

DeepSeek's absorbed-form decode attends in latent space: per sequence the
queries are ``q_lat [H, R]`` (nope-part absorbed through the K up-projection)
and ``q_rope [H, P]``; the paged cache stores compressed latents ``ck [bs, R]``
(doubling as the values) and rope keys ``kr [bs, P]`` per page.  Scores are
the two-part sum ``q_lat·ck + q_rope·kr`` and the context is accumulated in
latent space (decompression through the V up-projection happens outside).

Same pipelining scheme as ``paged_attention.py``: one grid step =
(sequence, page), page tiles DMA'd via the scalar-prefetched block table,
online-softmax accumulation in VMEM scratch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    # scalar prefetch
    block_tables_ref,   # [B, maxb] int32
    context_lens_ref,   # [B] int32
    # inputs
    q_lat_ref,          # [1, H, R]
    q_rope_ref,         # [1, H, P]
    ck_page_ref,        # [1, bs, R]   latents (keys AND values)
    kr_page_ref,        # [1, bs, P]   rope keys
    # output
    out_ref,            # [1, H, R]    latent-space context
    # scratch
    m_ref,              # [H, 128] f32 running max
    l_ref,              # [H, 128] f32 running denom
    acc_ref,            # [H, R]  f32 running numerator
    *,
    block_size: int,
    scale: float,
    max_blocks: int,
):
    seq = pl.program_id(0)
    page = pl.program_id(1)
    ctx = context_lens_ref[seq]

    @pl.when(page == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    page_start = page * block_size

    @pl.when(page_start < ctx)
    def _compute():
        q_lat = q_lat_ref[0].astype(jnp.float32)    # [H, R]
        q_rope = q_rope_ref[0].astype(jnp.float32)  # [H, P]
        ck = ck_page_ref[0].astype(jnp.float32)     # [bs, R]
        kr = kr_page_ref[0].astype(jnp.float32)     # [bs, P]
        # [H, bs] two-part scores, both contractions on the MXU
        s = (
            jax.lax.dot_general(
                q_lat, ck, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + jax.lax.dot_general(
                q_rope, kr, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        ) * scale
        pos = page_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        s = jnp.where(pos < ctx, s, NEG_INF)

        m_prev = m_ref[:, :1]                           # [H, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [H, bs]
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # [H, R] context in latent space: values ARE the latents
        pv = jax.lax.dot_general(
            p, ck, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(page == max_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


def _window_kernel(
    block_tables_ref,   # [B, maxb] int32
    context_lens_ref,   # [B] int32 — INCLUDING the window's last token
    q_lat_ref,          # [1, W*H, R]  (w-major fold: row = w*H + h)
    q_rope_ref,         # [1, W*H, P]
    ck_page_ref,        # [1, bs, R]
    kr_page_ref,        # [1, bs, P]
    out_ref,            # [1, W*H, R]
    m_ref,              # [W*H, 128] f32
    l_ref,
    acc_ref,            # [W*H, R] f32
    *,
    block_size: int,
    scale: float,
    max_blocks: int,
    window: int,
    num_heads: int,
):
    """Speculative-verification variant: W window queries fold into the
    head axis; each query row masks to its own absolute position."""
    seq = pl.program_id(0)
    page = pl.program_id(1)
    ctx = context_lens_ref[seq]
    wh = window * num_heads

    @pl.when(page == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    page_start = page * block_size

    @pl.when(page_start < ctx)
    def _compute():
        q_lat = q_lat_ref[0].astype(jnp.float32)    # [W*H, R]
        q_rope = q_rope_ref[0].astype(jnp.float32)
        ck = ck_page_ref[0].astype(jnp.float32)
        kr = kr_page_ref[0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q_lat, ck, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + jax.lax.dot_general(
                q_rope, kr, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        ) * scale                                    # [W*H, bs]
        pos = page_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        w_idx = jax.lax.broadcasted_iota(jnp.int32, (wh, 1), 0) // num_heads
        q_pos = ctx - window + w_idx                  # [W*H, 1]
        s = jnp.where(pos <= q_pos, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, ck, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(page == max_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def mla_paged_window_attention_decode(
    q_lat: jnp.ndarray,         # [B, W, H, R]
    q_rope: jnp.ndarray,        # [B, W, H, P]
    ck_cache: jnp.ndarray,      # [N, bs, R]
    kr_cache: jnp.ndarray,      # [N, bs, P]
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32 — INCLUDING the window's last token
    *,
    scale: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Multi-query MLA paged attention for speculative verification.
    Returns the latent-space context [B, W, H, R] (float32)."""
    b, w, h, r = q_lat.shape
    p_dim = q_rope.shape[-1]
    bs = ck_cache.shape[1]
    maxb = block_tables.shape[1]
    wh = w * h

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, wh, r), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, wh, p_dim), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, bs, r), lambda s, p, bt, cl: (bt[s, p], 0, 0)),
            pl.BlockSpec((1, bs, p_dim), lambda s, p, bt, cl: (bt[s, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, wh, r), lambda s, p, bt, cl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((wh, 128), jnp.float32),
            pltpu.VMEM((wh, 128), jnp.float32),
            pltpu.VMEM((wh, r), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _window_kernel, block_size=bs, scale=scale, max_blocks=maxb,
        window=w, num_heads=h,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, wh, r), jnp.float32),
        interpret=interpret,
    )(
        block_tables, context_lens,
        q_lat.reshape(b, wh, r), q_rope.reshape(b, wh, p_dim),
        ck_cache, kr_cache,
    )
    return out.reshape(b, w, h, r)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def mla_paged_attention_decode(
    q_lat: jnp.ndarray,         # [B, H, R] f32/bf16
    q_rope: jnp.ndarray,        # [B, H, P]
    ck_cache: jnp.ndarray,      # [N, bs, R] latent cache
    kr_cache: jnp.ndarray,      # [N, bs, P] rope-key cache
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32
    *,
    scale: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Returns the latent-space context [B, H, R] (float32)."""
    b, h, r = q_lat.shape
    p_dim = q_rope.shape[-1]
    bs = ck_cache.shape[1]
    maxb = block_tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, h, r), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, h, p_dim), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, bs, r), lambda s, p, bt, cl: (bt[s, p], 0, 0)),
            pl.BlockSpec((1, bs, p_dim), lambda s, p, bt, cl: (bt[s, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, r), lambda s, p, bt, cl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, r), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, block_size=bs, scale=scale, max_blocks=maxb
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, r), jnp.float32),
        interpret=interpret,
    )(block_tables, context_lens, q_lat, q_rope, ck_cache, kr_cache)
