"""MLA (multi-head latent attention) paged decode — Pallas TPU kernel.

DeepSeek's absorbed-form decode attends in latent space: per sequence the
queries are ``q_lat [H, R]`` (nope-part absorbed through the K up-projection)
and ``q_rope [H, P]``; the paged cache stores compressed latents ``ck [bs, R]``
(doubling as the values) and rope keys ``kr [bs, P]`` per page.  Scores are
the two-part sum ``q_lat·ck + q_rope·kr`` and the context is accumulated in
latent space (decompression through the V up-projection happens outside).

Same pipelining scheme as ``paged_attention.py``: one grid step =
(sequence, page), page tiles DMA'd via the scalar-prefetched block table,
online-softmax accumulation in VMEM scratch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    # scalar prefetch
    block_tables_ref,   # [B, maxb] int32
    context_lens_ref,   # [B] int32
    # inputs
    q_lat_ref,          # [1, H, R]
    q_rope_ref,         # [1, H, P]
    *refs,              # pps × (ck_page [1, bs, R], kr_page [1, bs, P]),
                        # out [1, H, R], then m/l/acc scratch
    block_size: int,
    scale: float,
    max_blocks: int,
    pages_per_step: int,
):
    pps = pages_per_step
    kv_refs = refs[: 2 * pps]
    out_ref = refs[2 * pps]
    m_ref, l_ref, acc_ref = refs[2 * pps + 1:]
    seq = pl.program_id(0)
    step = pl.program_id(1)
    ctx = context_lens_ref[seq]

    @pl.when(step == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for i in range(pps):
        page = step * pps + i
        page_start = page * block_size
        ck_page_ref = kv_refs[2 * i]
        kr_page_ref = kv_refs[2 * i + 1]

        @pl.when(page_start < ctx)
        def _compute(
            ck_page_ref=ck_page_ref, kr_page_ref=kr_page_ref,
            page_start=page_start,
        ):
            q_lat = q_lat_ref[0].astype(jnp.float32)    # [H, R]
            q_rope = q_rope_ref[0].astype(jnp.float32)  # [H, P]
            ck = ck_page_ref[0].astype(jnp.float32)     # [bs, R]
            kr = kr_page_ref[0].astype(jnp.float32)     # [bs, P]
            # [H, bs] two-part scores, both contractions on the MXU
            s = (
                jax.lax.dot_general(
                    q_lat, ck, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                + jax.lax.dot_general(
                    q_rope, kr, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            ) * scale
            pos = page_start + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_size), 1
            )
            s = jnp.where(pos < ctx, s, NEG_INF)

            m_prev = m_ref[:, :1]                       # [H, 1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)                      # [H, bs]
            l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            # [H, R] context in latent space: values ARE the latents
            pv = jax.lax.dot_general(
                p, ck, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[...] = acc_ref[...] * alpha + pv
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(step == -(-max_blocks // pps) - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


def _window_kernel(
    block_tables_ref,   # [B, maxb] int32
    context_lens_ref,   # [B] int32 — INCLUDING the window's last token
    q_lat_ref,          # [1, W*H, R]  (w-major fold: row = w*H + h)
    q_rope_ref,         # [1, W*H, P]
    ck_page_ref,        # [1, bs, R]
    kr_page_ref,        # [1, bs, P]
    out_ref,            # [1, W*H, R]
    m_ref,              # [W*H, 128] f32
    l_ref,
    acc_ref,            # [W*H, R] f32
    *,
    block_size: int,
    scale: float,
    max_blocks: int,
    window: int,
    num_heads: int,
):
    """Speculative-verification variant: W window queries fold into the
    head axis; each query row masks to its own absolute position."""
    seq = pl.program_id(0)
    page = pl.program_id(1)
    ctx = context_lens_ref[seq]
    wh = window * num_heads

    @pl.when(page == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    page_start = page * block_size

    @pl.when(page_start < ctx)
    def _compute():
        q_lat = q_lat_ref[0].astype(jnp.float32)    # [W*H, R]
        q_rope = q_rope_ref[0].astype(jnp.float32)
        ck = ck_page_ref[0].astype(jnp.float32)
        kr = kr_page_ref[0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q_lat, ck, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + jax.lax.dot_general(
                q_rope, kr, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        ) * scale                                    # [W*H, bs]
        pos = page_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        w_idx = jax.lax.broadcasted_iota(jnp.int32, (wh, 1), 0) // num_heads
        q_pos = ctx - window + w_idx                  # [W*H, 1]
        s = jnp.where(pos <= q_pos, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, ck, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(page == max_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def mla_paged_window_attention_decode(
    q_lat: jnp.ndarray,         # [B, W, H, R]
    q_rope: jnp.ndarray,        # [B, W, H, P]
    ck_cache: jnp.ndarray,      # [N, bs, R]
    kr_cache: jnp.ndarray,      # [N, bs, P]
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32 — INCLUDING the window's last token
    *,
    scale: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Multi-query MLA paged attention for speculative verification.
    Returns the latent-space context [B, W, H, R] (float32)."""
    b, w, h, r = q_lat.shape
    p_dim = q_rope.shape[-1]
    bs = ck_cache.shape[1]
    maxb = block_tables.shape[1]
    wh = w * h

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, wh, r), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, wh, p_dim), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, bs, r), lambda s, p, bt, cl: (bt[s, p], 0, 0)),
            pl.BlockSpec((1, bs, p_dim), lambda s, p, bt, cl: (bt[s, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, wh, r), lambda s, p, bt, cl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((wh, 128), jnp.float32),
            pltpu.VMEM((wh, 128), jnp.float32),
            pltpu.VMEM((wh, r), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _window_kernel, block_size=bs, scale=scale, max_blocks=maxb,
        window=w, num_heads=h,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, wh, r), jnp.float32),
        interpret=interpret,
    )(
        block_tables, context_lens,
        q_lat.reshape(b, wh, r), q_rope.reshape(b, wh, p_dim),
        ck_cache, kr_cache,
    )
    return out.reshape(b, w, h, r)


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "pages_per_step")
)
def mla_paged_attention_decode(
    q_lat: jnp.ndarray,         # [B, H, R] f32/bf16
    q_rope: jnp.ndarray,        # [B, H, P]
    ck_cache: jnp.ndarray,      # [N, bs, R] latent cache
    kr_cache: jnp.ndarray,      # [N, bs, P] rope-key cache
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32
    *,
    scale: float,
    interpret: bool = False,
    pages_per_step: int = 1,
) -> jnp.ndarray:
    """Returns the latent-space context [B, H, R] (float32).
    ``pages_per_step`` widens each grid step to DMA that many block-table
    pages (autotuned; past-the-end indices clamp to the last block)."""
    b, h, r = q_lat.shape
    p_dim = q_rope.shape[-1]
    bs = ck_cache.shape[1]
    maxb = block_tables.shape[1]
    pps = pages_per_step
    if pps < 1:
        raise ValueError(f"pages_per_step must be >= 1, got {pps}")
    pps = min(pps, maxb)

    def kv_map_at(i):
        def kv_map(s, p, bt, cl):
            return (bt[s, jnp.minimum(p * pps + i, maxb - 1)], 0, 0)
        return kv_map

    kv_specs = []
    for i in range(pps):
        m = kv_map_at(i)
        kv_specs += [
            pl.BlockSpec((1, bs, r), m),
            pl.BlockSpec((1, bs, p_dim), m),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, -(-maxb // pps)),
        in_specs=[
            pl.BlockSpec((1, h, r), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, h, p_dim), lambda s, p, bt, cl: (s, 0, 0)),
            *kv_specs,
        ],
        out_specs=pl.BlockSpec((1, h, r), lambda s, p, bt, cl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, r), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, block_size=bs, scale=scale, max_blocks=maxb,
        pages_per_step=pps,
    )
    kv_args = []
    for _ in range(pps):
        kv_args += [ck_cache, kr_cache]
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, r), jnp.float32),
        interpret=interpret,
    )(block_tables, context_lens, q_lat, q_rope, *kv_args)


def _ragged_kernel(
    token_lane_ref,     # [T] int32 — owning lane per token (OOB = pad)
    token_pos_ref,      # [T] int32 — absolute position per token (-1 = pad)
    page_phys_ref,      # [num_tb, PS] int32 — physical page per grid step
    page_lane_ref,      # [num_tb, PS] int32 — lane owning that page
    page_ord_ref,       # [num_tb, PS] int32 — page ordinal in its lane
    page_count_ref,     # [num_tb] int32 — live worklist entries
    q_lat_ref,          # [1, TB*H, R]  (token-major fold: row = tok*H + h)
    q_rope_ref,         # [1, TB*H, P]
    *refs,              # pps × (ck_page [1, bs, R], kr_page [1, bs, P]),
                        # out [1, TB*H, R], then m/l/acc scratch
    block_size: int,
    scale: float,
    page_slots: int,
    tb_tokens: int,
    num_heads: int,
    pages_per_step: int,
):
    """Ragged unified-batch MLA: the packed page-worklist loop of
    ops/pallas/ragged_attention.py applied to the latent cache — two-part
    scores, latent-space accumulation (decompression outside).  Each grid
    step folds ``pages_per_step`` consecutive worklist slots into the
    running softmax (one input stream per slot)."""
    pps = pages_per_step
    kv_refs = refs[: 2 * pps]
    out_ref = refs[2 * pps]
    m_ref, l_ref, acc_ref = refs[2 * pps + 1:]
    t = pl.program_id(0)
    j = pl.program_id(1)
    tbh = tb_tokens * num_heads

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for i in range(pps):
        slot = j * pps + i
        page_lane = page_lane_ref[t, slot]
        page_start = page_ord_ref[t, slot] * block_size
        ck_page_ref = kv_refs[2 * i]
        kr_page_ref = kv_refs[2 * i + 1]

        @pl.when(slot < page_count_ref[t])
        def _compute(
            ck_page_ref=ck_page_ref, kr_page_ref=kr_page_ref,
            page_lane=page_lane, page_start=page_start,
        ):
            q_lat = q_lat_ref[0].astype(jnp.float32)    # [TB*H, R]
            q_rope = q_rope_ref[0].astype(jnp.float32)  # [TB*H, P]
            ck = ck_page_ref[0].astype(jnp.float32)     # [bs, R]
            kr = kr_page_ref[0].astype(jnp.float32)     # [bs, P]
            s = (
                jax.lax.dot_general(
                    q_lat, ck, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                + jax.lax.dot_general(
                    q_rope, kr, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            ) * scale                                    # [TB*H, bs]
            pos = page_start + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_size), 1
            )
            row = jax.lax.broadcasted_iota(jnp.int32, (tbh, 1), 0)
            tok_of_row = row // num_heads
            base = t * tb_tokens
            q_pos = jnp.full((tbh, 1), -1, jnp.int32)
            row_lane = jnp.full((tbh, 1), -1, jnp.int32)
            for rr in range(tb_tokens):
                q_pos = jnp.where(
                    tok_of_row == rr, token_pos_ref[base + rr], q_pos
                )
                row_lane = jnp.where(
                    tok_of_row == rr, token_lane_ref[base + rr], row_lane
                )
            mask = (row_lane == page_lane) & (pos <= q_pos)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = m_ref[:, :1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p, ck, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[...] = acc_ref[...] * alpha + pv
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == page_slots // pps - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "tb_tokens", "pages_per_step", "interpret"),
)
def ragged_mla_attention(
    q_lat: jnp.ndarray,         # [T, H, R] flat ragged token batch
    q_rope: jnp.ndarray,        # [T, H, P]
    ck_cache: jnp.ndarray,      # [N, bs, R] latent cache (keys AND values)
    kr_cache: jnp.ndarray,      # [N, bs, P] rope-key cache
    token_lane: jnp.ndarray,    # [T] int32 owning lane (OOB = pad)
    token_pos: jnp.ndarray,     # [T] int32 absolute position (-1 = pad)
    page_phys: jnp.ndarray,     # [T // tb_tokens, PS] int32 (pack_page_meta)
    page_lane: jnp.ndarray,     # [T // tb_tokens, PS] int32
    page_ord: jnp.ndarray,      # [T // tb_tokens, PS] int32
    page_count: jnp.ndarray,    # [T // tb_tokens] int32
    *,
    scale: float,
    tb_tokens: int = 8,
    pages_per_step: int = 1,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged unified-batch MLA paged attention with packed lanes: one
    launch over mixed chunked-prefill spans + decode tokens against the
    latent cache.  Returns the latent-space context [T, H, R] (float32);
    metadata comes from ragged_attention.pack_page_meta over the latent
    block tables.  ``pages_per_step`` widens each grid step to DMA that
    many worklist pages (autotuned; ``page_slots`` must divide evenly)."""
    t_pad, h, r = q_lat.shape
    p_dim = q_rope.shape[-1]
    bs = ck_cache.shape[1]
    if t_pad % tb_tokens:
        raise ValueError(
            f"flat token axis ({t_pad}) must pack whole token blocks of "
            f"{tb_tokens}"
        )
    num_tb = t_pad // tb_tokens
    page_slots = page_phys.shape[1]
    pps = pages_per_step
    if pps < 1 or page_slots % pps:
        raise ValueError(
            f"page_slots ({page_slots}) must be a positive multiple of "
            f"pages_per_step ({pps})"
        )
    tbh = tb_tokens * h

    def kv_map_at(i):
        def kv_map(t, j, tl, tp, pp, pln, po, pc):
            return (pp[t, j * pps + i], 0, 0)
        return kv_map

    kv_specs = []
    for i in range(pps):
        m = kv_map_at(i)
        kv_specs += [
            pl.BlockSpec((1, bs, r), m),
            pl.BlockSpec((1, bs, p_dim), m),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(num_tb, page_slots // pps),
        in_specs=[
            pl.BlockSpec((1, tbh, r), lambda t, j, *_: (t, 0, 0)),
            pl.BlockSpec((1, tbh, p_dim), lambda t, j, *_: (t, 0, 0)),
            *kv_specs,
        ],
        out_specs=pl.BlockSpec((1, tbh, r), lambda t, j, *_: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tbh, 128), jnp.float32),
            pltpu.VMEM((tbh, 128), jnp.float32),
            pltpu.VMEM((tbh, r), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel,
        block_size=bs,
        scale=scale,
        page_slots=page_slots,
        tb_tokens=tb_tokens,
        num_heads=h,
        pages_per_step=pps,
    )
    kv_args = []
    for _ in range(pps):
        kv_args += [ck_cache, kr_cache]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_tb, tbh, r), jnp.float32),
        interpret=interpret,
    )(
        token_lane, token_pos, page_phys, page_lane, page_ord, page_count,
        q_lat.reshape(num_tb, tbh, r),
        q_rope.reshape(num_tb, tbh, p_dim),
        *kv_args,
    )
    return out.reshape(t_pad, h, r)
