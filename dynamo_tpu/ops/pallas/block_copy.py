"""Batched KV block gather/scatter — Pallas TPU kernel.

The TPU-native replacement for the reference's CUDA block-copy kernel
(lib/llm/src/kernels/block_copy.cu ``copy_blocks_kernel``): moves a batch of
blocks between cache pools by id list.  The BlockSpec index maps do the
indirection from scalar-prefetched id arrays; Pallas pipelines the HBM↔VMEM
DMAs across grid steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gather_kernel(src_ids_ref, pool_ref, out_ref):
    out_ref[...] = pool_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_blocks(
    pool: jnp.ndarray,      # [N, *block]
    src_ids: jnp.ndarray,   # [n] int32
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """out[i] = pool[src_ids[i]] — block extraction for transfer/offload."""
    n = src_ids.shape[0]
    block = pool.shape[1:]
    rest = (0,) * len(block)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, *block), lambda i, ids: (ids[i], *rest))],
        out_specs=pl.BlockSpec((1, *block), lambda i, ids: (i, *rest)),
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, *block), pool.dtype),
        interpret=interpret,
    )(src_ids, pool)


def _scatter_kernel(dst_ids_ref, blocks_ref, pool_ref, out_ref):
    # pool_ref is the aliased destination (HBM, untouched here); each grid
    # step writes one transferred block into its target slot
    out_ref[...] = blocks_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def scatter_blocks(
    pool: jnp.ndarray,      # [N, *block] (donated)
    blocks: jnp.ndarray,    # [n, *block]
    dst_ids: jnp.ndarray,   # [n] int32
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """pool[dst_ids[i]] = blocks[i] — block injection (transfer landing)."""
    n = dst_ids.shape[0]
    block = pool.shape[1:]
    rest = (0,) * len(block)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, *block), lambda i, ids: (i, *rest)),
            pl.BlockSpec(memory_space=pl.ANY),  # aliased pool, not loaded
        ],
        out_specs=pl.BlockSpec((1, *block), lambda i, ids: (ids[i], *rest)),
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        interpret=interpret,
        input_output_aliases={2: 0},  # pool (operand 2 incl. prefetch) → out
    )(dst_ids, blocks, pool)
