"""Pallas TPU kernels for the hot paths.

- ``paged_attention``: decode-step attention reading KV pages from HBM via
  scalar-prefetched block tables — no materialized gather (the pure-JAX
  fallback in ``dynamo_tpu.ops.attention`` gathers [B, max_len] into HBM).
- ``block_copy``: batched KV block gather/scatter between cache pools
  (replaces the reference's CUDA block-copy kernel,
  lib/llm/src/kernels/block_copy.cu, with a TPU-native kernel).

Kernels run in interpret mode on CPU (tests) and compiled on TPU.
"""

from dynamo_tpu.ops.pallas.paged_attention import (
    paged_attention_decode,
    paged_window_attention_decode,
)
from dynamo_tpu.ops.pallas.ragged_attention import (
    pack_page_meta,
    ragged_paged_attention,
)
from dynamo_tpu.ops.pallas.mla_attention import ragged_mla_attention
from dynamo_tpu.ops.pallas.block_copy import gather_blocks, scatter_blocks

__all__ = [
    "paged_attention_decode",
    "paged_window_attention_decode",
    "ragged_paged_attention",
    "ragged_mla_attention",
    "pack_page_meta",
    "gather_blocks",
    "scatter_blocks",
]
