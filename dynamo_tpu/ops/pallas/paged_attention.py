"""Paged decode attention — Pallas TPU kernel.

One grid step = (sequence, page): the page's K/V tiles are pipelined from
HBM into VMEM by the BlockSpec index_map reading the scalar-prefetched block
table (so the "gather" is just DMA addressing), and softmax is accumulated
online flash-style in VMEM scratch across a sequence's pages.

Layout notes (TPU tiling):
- K/V cache pages are [block_size, kv_heads*head_dim] per page after
  flattening heads into the lane dimension (head_dim multiple of 128 keeps
  lanes aligned; block_size ≥ 8 keeps sublanes aligned).
- GQA: queries [kv_heads*group, head_dim]; per page we contract
  [G_all, D] × [bs, KVH, D] per kv head.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    # scalar prefetch
    block_tables_ref,   # [B, maxb] int32
    context_lens_ref,   # [B] int32
    # inputs
    q_ref,              # [1, H, D]        (this sequence's queries)
    k_page_ref,         # [1, bs, KVH, D]  (this grid step's page)
    v_page_ref,
    # output
    out_ref,            # [1, H, D]
    # scratch
    m_ref,              # [KVH, G, 128] f32 running max (broadcast on lanes)
    l_ref,              # [KVH, G, 128] f32 running denom
    acc_ref,            # [KVH, G, D] f32 running numerator
    *,
    block_size: int,
    num_kv_heads: int,
    groups: int,
    head_dim: int,
    max_blocks: int,
):
    seq = pl.program_id(0)
    page = pl.program_id(1)
    ctx = context_lens_ref[seq]

    @pl.when(page == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    page_start = page * block_size

    @pl.when(page_start < ctx)
    def _compute():
        q = q_ref[0].reshape(num_kv_heads, groups, head_dim).astype(jnp.float32)
        k = k_page_ref[0].astype(jnp.float32)   # [bs, KVH, D]
        v = v_page_ref[0].astype(jnp.float32)
        scale = 1.0 / (head_dim ** 0.5)
        # [KVH, G, bs] = batch(KVH) contract(D)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale
        pos = page_start + jax.lax.broadcasted_iota(jnp.int32, (1, 1, block_size), 2)
        s = jnp.where(pos < ctx, s, NEG_INF)

        m_prev = m_ref[:, :, :1]                            # [KVH, G, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # [KVH, G, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                              # [KVH, G, bs]
        l_new = l_ref[:, :, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # [KVH, G, D] = batch(KVH) contract(bs)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(page == max_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :, :1], 1e-20)
        out = (acc_ref[...] / denom).reshape(num_kv_heads * groups, head_dim)
        out_ref[0] = out.astype(out_ref.dtype)


def _window_kernel(
    block_tables_ref,   # [B, maxb] int32
    context_lens_ref,   # [B] int32 — INCLUDING the window's last token
    q_ref,              # [1, W, H, D]
    k_page_ref,         # [1, bs, KVH, D]
    v_page_ref,
    out_ref,            # [1, W, H, D]
    m_ref,              # [KVH, W*G, 128] f32
    l_ref,
    acc_ref,            # [KVH, W*G, D] f32
    *,
    block_size: int,
    num_kv_heads: int,
    groups: int,
    head_dim: int,
    max_blocks: int,
    window: int,
):
    """Multi-query (speculative verification) variant: the W window queries
    fold into the group axis — one extra mask term per query position,
    otherwise the same online-softmax page loop as ``_kernel``."""
    seq = pl.program_id(0)
    page = pl.program_id(1)
    ctx = context_lens_ref[seq]
    wg = window * groups

    @pl.when(page == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    page_start = page * block_size

    @pl.when(page_start < ctx)
    def _compute():
        # [W, KVH, G, D] → [KVH, W, G, D] → [KVH, W*G, D]
        q = (
            q_ref[0]
            .reshape(window, num_kv_heads, groups, head_dim)
            .transpose(1, 0, 2, 3)
            .reshape(num_kv_heads, wg, head_dim)
            .astype(jnp.float32)
        )
        k = k_page_ref[0].astype(jnp.float32)
        v = v_page_ref[0].astype(jnp.float32)
        scale = 1.0 / (head_dim ** 0.5)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale                                            # [KVH, W*G, bs]
        pos = page_start + jax.lax.broadcasted_iota(jnp.int32, (1, 1, block_size), 2)
        w_idx = jax.lax.broadcasted_iota(jnp.int32, (1, wg, 1), 1) // groups
        q_pos = ctx - window + w_idx                          # [1, W*G, 1]
        s = jnp.where(pos <= q_pos, s, NEG_INF)

        m_prev = m_ref[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_ref[:, :, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(page == max_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :, :1], 1e-20)
        out = (
            (acc_ref[...] / denom)
            .reshape(num_kv_heads, window, groups, head_dim)
            .transpose(1, 0, 2, 3)
            .reshape(window, num_kv_heads * groups, head_dim)
        )
        out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_window_attention_decode(
    q: jnp.ndarray,            # [B, W, H, D]
    k_cache: jnp.ndarray,      # [N, bs, KVH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32 — INCLUDING the window's last token
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas multi-query paged attention for speculative verification
    (pure-JAX twin: ops/attention.py paged_window_attention)."""
    b, w, h, d = q.shape
    _, bs, kvh, _ = k_cache.shape
    maxb = block_tables.shape[1]
    groups = h // kvh

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, w, h, d), lambda s, p, bt, cl: (s, 0, 0, 0)),
            pl.BlockSpec((1, bs, kvh, d), lambda s, p, bt, cl: (bt[s, p], 0, 0, 0)),
            pl.BlockSpec((1, bs, kvh, d), lambda s, p, bt, cl: (bt[s, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, w, h, d), lambda s, p, bt, cl: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, w * groups, 128), jnp.float32),
            pltpu.VMEM((kvh, w * groups, 128), jnp.float32),
            pltpu.VMEM((kvh, w * groups, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _window_kernel,
        block_size=bs,
        num_kv_heads=kvh,
        groups=groups,
        head_dim=d,
        max_blocks=maxb,
        window=w,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, w, h, d), q.dtype),
        interpret=interpret,
    )(block_tables, context_lens, q, k_cache, v_cache)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_decode(
    q: jnp.ndarray,            # [B, H, D]
    k_cache: jnp.ndarray,      # [N, bs, KVH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    b, h, d = q.shape
    _, bs, kvh, _ = k_cache.shape
    maxb = block_tables.shape[1]
    groups = h // kvh

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxb),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda s, p, bt, cl: (s, 0, 0)),
            pl.BlockSpec((1, bs, kvh, d), lambda s, p, bt, cl: (bt[s, p], 0, 0, 0)),
            pl.BlockSpec((1, bs, kvh, d), lambda s, p, bt, cl: (bt[s, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda s, p, bt, cl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, groups, 128), jnp.float32),
            pltpu.VMEM((kvh, groups, 128), jnp.float32),
            pltpu.VMEM((kvh, groups, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel,
        block_size=bs,
        num_kv_heads=kvh,
        groups=groups,
        head_dim=d,
        max_blocks=maxb,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(block_tables, context_lens, q, k_cache, v_cache)
