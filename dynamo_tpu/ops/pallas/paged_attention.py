"""Paged decode attention — Pallas TPU kernel.

One grid step = (sequence, page): the page's K/V tiles are pipelined from
HBM into VMEM by the BlockSpec index_map reading the scalar-prefetched block
table (so the "gather" is just DMA addressing), and softmax is accumulated
online flash-style in VMEM scratch across a sequence's pages.

Layout notes (TPU tiling / Mosaic):
- A cache page [bs, KVH, D] is viewed flat as [bs*KVH, D] (an HBM reshape,
  free) so every matmul in the kernel is plain 2-D — Mosaic's tpu.matmul
  does not accept batched operands whose batch dims sit at different
  positions, which is exactly what a per-kv-head batched dot over
  [KVH, G, D] × [bs, KVH, D] lowers to.
- GQA head matching is done with iota masks on the score matrix
  [H, bs*KVH]: column j*KVH+c holds page position j of kv head c, and query
  head h only keeps columns with c == h // groups.  The masked entries cost
  KVH× extra MACs, but decode attention is HBM-bandwidth-bound (the page
  streams dominate) and the whole score matmul is a single MXU tile pass,
  so the "waste" is free in wall-clock terms.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _window_kernel(
    block_tables_ref,   # [B, maxb] int32
    context_lens_ref,   # [B] int32 — INCLUDING the window's last token
    q_ref,              # [1, W*H, D]   (w-major fold: row = w*H + h)
    *refs,              # pps × (k_page [1, bs*KVH, D], v_page), out, scratch
    block_size: int,
    num_kv_heads: int,
    groups: int,
    head_dim: int,
    max_blocks: int,
    window: int,
    pages_per_step: int,
    sliding_window: int | None,
):
    """Online-softmax page loop over flat [bs*KVH, D] pages.  The W window
    queries (W=1 for plain decode) fold into the row axis; each query row
    masks to its own absolute position.  ``sliding_window`` (Mistral-style)
    additionally drops positions more than W_s-1 behind each query.
    ``pages_per_step`` consecutive pages ride one grid step, each as its
    own input stream (the index maps clamp past-the-end page indices to
    the last block; their compute is gated off here)."""
    pps = pages_per_step
    kv_refs = refs[: 2 * pps]
    out_ref = refs[2 * pps]
    m_ref, l_ref, acc_ref = refs[2 * pps + 1:]
    seq = pl.program_id(0)
    step = pl.program_id(1)
    ctx = context_lens_ref[seq]
    rows = block_size * num_kv_heads
    h_all = num_kv_heads * groups
    wh = window * h_all

    @pl.when(step == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for i in range(pps):
        page = step * pps + i
        page_start = page * block_size
        k_page_ref = kv_refs[2 * i]
        v_page_ref = kv_refs[2 * i + 1]

        # ctx <= max_blocks * block_size, so past-the-end pages (page >=
        # max_blocks when pps does not divide maxb) fail this gate too
        active = page_start < ctx
        if sliding_window is not None:
            # pages entirely below every query's window contribute
            # nothing — skip their compute (their DMA is also deduped:
            # the index_map clamps them to the first in-window page).
            # Lowest visible absolute position =
            # (ctx - window) - (sliding_window - 1).
            active &= (
                page_start + block_size > ctx - window - (sliding_window - 1)
            )

        @pl.when(active)
        def _compute(
            k_page_ref=k_page_ref, v_page_ref=v_page_ref,
            page_start=page_start,
        ):
            q = q_ref[0].astype(jnp.float32)        # [W*H, D]
            k = k_page_ref[0].astype(jnp.float32)   # [bs*KVH, D]
            v = v_page_ref[0].astype(jnp.float32)
            scale = 1.0 / (head_dim ** 0.5)
            s = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                    # [W*H, bs*KVH]
            col = jax.lax.broadcasted_iota(jnp.int32, (1, rows), 1)
            pos = page_start + col // num_kv_heads
            kv_of_col = col % num_kv_heads
            row = jax.lax.broadcasted_iota(jnp.int32, (wh, 1), 0)
            kv_of_row = (row % h_all) // groups
            q_pos = ctx - window + row // h_all          # [W*H, 1]
            mask = (kv_of_col == kv_of_row) & (pos <= q_pos)
            if sliding_window is not None:
                mask = mask & (pos > q_pos - sliding_window)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = m_ref[:, :1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p, v,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[...] = acc_ref[...] * alpha + pv
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(step == -(-max_blocks // pps) - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        out_ref[0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("interpret", "sliding_window", "pages_per_step"),
)
def paged_window_attention_decode(
    q: jnp.ndarray,            # [B, W, H, D]
    k_cache: jnp.ndarray,      # [N, bs, KVH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32 — INCLUDING the window's last token
    *,
    interpret: bool = False,
    sliding_window: int | None = None,
    pages_per_step: int = 1,
) -> jnp.ndarray:
    """Pallas multi-query paged attention for speculative verification
    (pure-JAX twin: ops/attention.py paged_window_attention).
    ``pages_per_step`` widens each grid step to DMA that many block-table
    pages (autotuned; past-the-end indices clamp to the last block)."""
    b, w, h, d = q.shape
    n, bs, kvh, _ = k_cache.shape
    maxb = block_tables.shape[1]
    groups = h // kvh
    rows = bs * kvh
    wh = w * h
    pps = pages_per_step
    if pps < 1:
        raise ValueError(f"pages_per_step must be >= 1, got {pps}")
    pps = min(pps, maxb)

    if sliding_window is None:
        def kv_map_at(i):
            def kv_map(s, p, bt, cl):
                return (bt[s, jnp.minimum(p * pps + i, maxb - 1)], 0, 0)
            return kv_map
    else:
        def kv_map_at(i):
            def kv_map(s, p, bt, cl):
                # clamp below-window pages to the first in-window page:
                # the pipeline then re-fetches the same block instead of
                # streaming pages whose compute is skipped
                lowest = cl[s] - w - (sliding_window - 1)
                p_min = jnp.maximum(lowest, 0) // bs
                page = jnp.minimum(p * pps + i, maxb - 1)
                return (bt[s, jnp.maximum(page, p_min)], 0, 0)
            return kv_map

    kv_specs = []
    for i in range(pps):
        m = kv_map_at(i)
        kv_specs += [
            pl.BlockSpec((1, rows, d), m),
            pl.BlockSpec((1, rows, d), m),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, -(-maxb // pps)),
        in_specs=[
            pl.BlockSpec((1, wh, d), lambda s, p, bt, cl: (s, 0, 0)),
            *kv_specs,
        ],
        out_specs=pl.BlockSpec((1, wh, d), lambda s, p, bt, cl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((wh, 128), jnp.float32),
            pltpu.VMEM((wh, 128), jnp.float32),
            pltpu.VMEM((wh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _window_kernel,
        block_size=bs,
        num_kv_heads=kvh,
        groups=groups,
        head_dim=d,
        max_blocks=maxb,
        window=w,
        pages_per_step=pps,
        sliding_window=sliding_window,
    )
    k_flat = k_cache.reshape(n, rows, d)
    v_flat = v_cache.reshape(n, rows, d)
    kv_args = []
    for _ in range(pps):
        kv_args += [k_flat, v_flat]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, wh, d), q.dtype),
        interpret=interpret,
    )(
        block_tables, context_lens,
        q.reshape(b, wh, d),
        *kv_args,
    )
    return out.reshape(b, w, h, d)


@functools.partial(
    jax.jit,
    static_argnames=("interpret", "sliding_window", "pages_per_step"),
)
def paged_attention_decode(
    q: jnp.ndarray,            # [B, H, D]
    k_cache: jnp.ndarray,      # [N, bs, KVH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, maxb] int32
    context_lens: jnp.ndarray,  # [B] int32
    *,
    interpret: bool = False,
    sliding_window: int | None = None,
    pages_per_step: int = 1,
) -> jnp.ndarray:
    # plain decode is the window kernel at W=1: `pos <= ctx - 1` ≡ `pos < ctx`
    out = paged_window_attention_decode(
        q[:, None], k_cache, v_cache, block_tables, context_lens,
        interpret=interpret, sliding_window=sliding_window,
        pages_per_step=pages_per_step,
    )
    return out[:, 0]
