#!/usr/bin/env python
"""dynlint — project-native static analysis for dynamo-tpu.

Pure-AST, stdlib-only (no JAX import): safe and fast as a tier-1 gate.

Usage::

    python scripts/dynlint.py --check             # the CI gate
    python scripts/dynlint.py --write-baseline    # re-record accepted debt
    python scripts/dynlint.py --knob-table        # DYN_* docs table rows
    python scripts/dynlint.py --list              # print findings, no gate

``--check`` compares findings against ANALYSIS_BASELINE.json (the ratchet):
exit 1 on any NEW finding (not in the baseline) or any STALE baseline entry
(debt that no longer exists must be re-recorded so the baseline only shrinks
deliberately).  It also writes ANALYSIS_SUMMARY.json — per-pass finding and
suppression counts — so future PRs can diff analyzer debt.

See docs/analysis.md for the pass catalog and suppression syntax.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from dynamo_tpu import analysis  # noqa: E402
from dynamo_tpu.analysis import core  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="gate mode: fail on new/stale findings vs the baseline")
    parser.add_argument("--list", action="store_true",
                        help="print all current findings (no baseline compare)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record the current findings as the accepted baseline")
    parser.add_argument("--knob-table", nargs="?", const="all", default=None,
                        metavar="SECTION",
                        help="print the DYN_* knob table (markdown); optional "
                             "section filter, e.g. docs/performance.md")
    parser.add_argument("--passes", default=None,
                        help="comma-separated pass subset (default: all)")
    parser.add_argument("--baseline", default=str(REPO_ROOT / core.BASELINE_NAME))
    parser.add_argument("--summary", default=str(REPO_ROOT / core.SUMMARY_NAME))
    parser.add_argument("roots", nargs="*", default=list(analysis.DEFAULT_ROOTS),
                        help="directories/files to scan (default: dynamo_tpu scripts)")
    args = parser.parse_args(argv)

    if args.knob_table is not None:
        from dynamo_tpu.utils import knobs  # stdlib-only module; no JAX

        section = None if args.knob_table == "all" else args.knob_table
        print(knobs.knob_table(section))
        return 0

    passes = tuple(args.passes.split(",")) if args.passes else None
    findings, summary = analysis.analyze(
        REPO_ROOT, roots=tuple(args.roots), passes=passes
    )

    if args.write_baseline:
        core.write_baseline(Path(args.baseline), findings)
        print(f"wrote {args.baseline} ({len(findings)} finding(s) accepted as debt)")
        return 0

    if args.list or not args.check:
        for f in findings:
            print(f.render())
        print(f"\n{len(findings)} finding(s), {summary['suppressed']} suppressed; "
              f"per pass: {summary['per_pass']}")
        return 0 if not args.check else (1 if findings else 0)

    # --check: the ratchet
    baseline = core.load_baseline(Path(args.baseline))
    new, stale = core.diff_baseline(findings, baseline)
    summary["baselined"] = len(findings) - len(new)
    summary["new"] = len(new)
    summary["stale_baseline_entries"] = len(stale)
    Path(args.summary).write_text(json.dumps(summary, indent=2) + "\n")

    if new:
        print(f"dynlint: {len(new)} NEW finding(s) not in {Path(args.baseline).name}:")
        for f in new:
            print(f"  {f.render()}")
    if stale:
        print(f"dynlint: {len(stale)} STALE baseline entr(ies) — the debt they "
              "recorded no longer exists.  Re-record with --write-baseline:")
        for key in stale:
            print(f"  {key}")
    if new or stale:
        return 1
    print(f"dynlint: clean — {summary['files_scanned']} files, "
          f"{len(findings)} finding(s) all baselined, "
          f"{summary['suppressed']} suppressed; per pass: {summary['per_pass']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
