"""Measures speculative decoding × fused multi-step decode (round-3 review
item; the former decode_steps restriction is now LIFTED).

Both features amortize per-launch dispatch: fused decode scans
``decode_steps`` plain iterations on-device; speculative decoding verifies
a ``spec_tokens`` draft window in one launch.  They compose: iterations
with enough drafting lanes run the verify program, the rest (sampled
lanes, draft misses) run the fused multi-step program.  This script
records tok/s for each mode on the same engine geometry:
  - baseline:   decode_steps=1
  - fused:      decode_steps=W
  - spec:       ngram, spec_tokens=W-1 (verify window = W tokens)
  - composed:   ngram + decode_steps=W (the newly-allowed combination)
on three workloads: repetitive text (the drafter's best case — note the
tiny random-weight model's greedy output goes periodic, so even "random"
prompts eventually draft), random prompts, and SAMPLED decoding
(temperature > 0: lanes are draft-ineligible, so the spec engine's
fallback path carries all traffic — the regime the composed mode's fused
fallback exists for).

Run: ``python scripts/spec_vs_fused.py [--window 4] [--out JSON]``
(CPU works; numbers are labeled with the platform they came from.)
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def measure(mode: str, window: int, workload: str, *, osl: int = 96,
                  num_requests: int = 6) -> dict:
    import jax
    import numpy as np

    from dynamo_tpu.engine import EngineConfig, JaxLlmEngine
    from dynamo_tpu.llm.protocols.common import (
        Annotated,
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.llama import LlamaConfig, init_params
    from dynamo_tpu.runtime.engine import Context

    cfg = LlamaConfig.tiny()
    kwargs = {}
    if mode == "fused":
        kwargs["decode_steps"] = window
    elif mode == "spec":
        kwargs.update(speculative="ngram", spec_tokens=window - 1, spec_ngram=2)
    elif mode == "composed":
        kwargs.update(speculative="ngram", spec_tokens=window - 1, spec_ngram=2,
                      decode_steps=window)
    engine = JaxLlmEngine(
        EngineConfig(
            model=cfg, num_blocks=256, block_size=4, max_batch_size=4,
            prefill_buckets=(32,), max_model_len=160, top_logprobs_k=0,
            logit_bias_k=0, **kwargs,
        ),
        params=init_params(cfg, jax.random.PRNGKey(0)),
    )
    engine.start()
    rng = np.random.default_rng(0)

    def prompt() -> list[int]:
        if workload == "repetitive":
            # a short loop the greedy model tends to continue and the
            # ngram drafter locks onto
            pat = rng.integers(3, 40, size=4).tolist()
            return (pat * 8)[:32]
        return rng.integers(3, cfg.vocab_size - 3, size=32).tolist()

    async def drive(tokens: list[int], seed: int = 0) -> int:
        sampling = (
            SamplingOptions(temperature=0.9, seed=seed)
            if workload == "sampled"
            else SamplingOptions(use_greedy=True)
        )
        req = PreprocessedRequest(
            token_ids=tokens,
            sampling=sampling,
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
            eos_token_ids=[],
        )
        stream = await engine.generate(Context(req.to_wire()))
        count = 0
        async for item in stream:
            ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
            if ann.data is not None:
                count += len(ann.data.token_ids)
        return count

    try:
        await drive(prompt())  # warmup: compiles
        warm = engine.stats()  # counters must exclude the untimed warmup
        t0 = time.monotonic()
        counts = await asyncio.gather(
            *[drive(prompt(), seed=i + 1) for i in range(num_requests)]
        )
        wall = time.monotonic() - t0
        stats = engine.stats()
        delta = lambda k: stats.get(k, 0) - warm.get(k, 0)  # noqa: E731
        return {
            "mode": mode,
            "workload": workload,
            "tok_s": round(sum(counts) / wall, 1),
            "tokens": sum(counts),
            "wall_s": round(wall, 2),
            "spec_accepted": delta("spec_accepted_tokens_total"),
            "spec_drafted": delta("spec_drafted_tokens_total"),
        }
    finally:
        engine.stop()


async def amain(window: int) -> dict:
    import jax

    out = {
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "window": window,
        "results": [],
    }
    for workload in ("repetitive", "random", "sampled"):
        for mode in ("baseline", "fused", "spec", "composed"):
            row = await measure(mode, window, workload)
            print(json.dumps(row))
            sys.stdout.flush()
            out["results"].append(row)
    rows = {(r["mode"], r["workload"]): r for r in out["results"]}
    r = lambda m, w, base: round(  # noqa: E731
        rows[(m, w)]["tok_s"] / rows[(base, w)]["tok_s"], 2
    )
    out["verdict"] = {
        "fused_vs_baseline_repetitive": r("fused", "repetitive", "baseline"),
        "spec_vs_baseline_repetitive": r("spec", "repetitive", "baseline"),
        "composed_vs_spec_repetitive": r("composed", "repetitive", "spec"),
        "spec_vs_baseline_random": r("spec", "random", "baseline"),
        "composed_vs_spec_random": r("composed", "random", "spec"),
        # the lifted restriction's payoff: draft-ineligible (sampled)
        # traffic on a spec engine rides the FUSED fallback when composed
        "composed_vs_spec_sampled": r("composed", "sampled", "spec"),
        "fused_vs_baseline_sampled": r("fused", "sampled", "baseline"),
    }
    return out


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--window", type=int, default=4)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    result = asyncio.run(amain(args.window))
    print(json.dumps(result["verdict"]))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
