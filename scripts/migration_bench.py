#!/usr/bin/env python
"""Migration bench: run the shipped ``migration`` scenario and distill the
headline numbers into ``MIGRATION_BENCH.json``.

The scenario (dynamo_tpu/scenarios/specs/migration.json) soaks a routed
3-worker mocker fleet and live-migrates sessions mid-decode three ways —
explicit migration events under load, a graceful drain under load, and the
planner's defragmentation loop over a long-context phase.  The runner
verifies every completed stream byte-for-byte against the unmigrated greedy
reference (``verify_outputs``), so the bench's "zero loss" and
"byte-identical" claims come straight from the artifact, not from a second
reference run.

The headline defrag measurement is a controlled A/B: the long-context
``defrag`` phase is re-run with the defrag loop disabled (same seed, same
traffic) and the cross-worker KV-occupancy variance (``kv_occ_var`` in the
tick series) averaged over the phase is compared — the planner loop is
doing its job when the variance with defrag ON sits below the OFF control.

Usage::

    JAX_PLATFORMS=cpu python scripts/migration_bench.py \
        [--out MIGRATION_BENCH.json] [--speedup 8.0]

Exit code 0 = scenario passed and wrote the artifact; 1 = a phase failed
(the artifact is still written, with ``passed: false``).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).parent.parent
if str(_REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(_REPO_ROOT))


def _mean(xs: list[float]) -> float:
    return sum(xs) / len(xs) if xs else 0.0


def _phase_var(artifact: dict, phase: str) -> tuple[float, float, int]:
    """(mean kv_occ_var, mean kv_occ_spread, tick count) over one phase."""
    ticks = [
        t for t in artifact.get("ticks", [])
        if t.get("phase") == phase and "kv_occ_var" in t
    ]
    return (
        _mean([t["kv_occ_var"] for t in ticks]),
        _mean([t["kv_occ_spread"] for t in ticks]),
        len(ticks),
    )


def summarize(artifact: dict, control: dict | None = None) -> dict:
    """Distill a migration-scenario artifact (plus the optional
    defrag-disabled control run of the defrag phase) into the bench
    record."""
    phases = artifact.get("phases", [])
    by_name = {p["name"]: p for p in phases}
    migrations = dict(artifact.get("migrations") or {})
    moves = migrations.pop("defrag_moves", []) or []

    variance: dict = {}
    on_var, on_spread, on_ticks = _phase_var(artifact, "defrag")
    if on_ticks:
        variance = {
            "phase": "defrag",
            "defrag_on": {
                "kv_occ_var": round(on_var, 6),
                "kv_occ_spread": round(on_spread, 4),
                "ticks": on_ticks,
            },
        }
    if control is not None:
        off_var, off_spread, off_ticks = _phase_var(control, "defrag")
        variance["defrag_off"] = {
            "kv_occ_var": round(off_var, 6),
            "kv_occ_spread": round(off_spread, 4),
            "ticks": off_ticks,
        }
        variance["kv_occ_var_drop"] = round(off_var - on_var, 6)
        variance["kv_occ_var_drop_ratio"] = (
            round((off_var - on_var) / off_var, 4) if off_var else 0.0
        )

    outputs = {
        "verified": sum(
            (p.get("outputs") or {}).get("verified", 0) for p in phases
        ),
        "corrupt": sum(
            (p.get("outputs") or {}).get("corrupt", 0) for p in phases
        ),
    }
    requests = {
        "completed": sum(p["requests"]["completed"] for p in phases),
        "failed": sum(p["requests"]["failed"] for p in phases),
    }
    return {
        "scenario": artifact.get("scenario"),
        "passed": bool(artifact.get("passed")),
        "requests": requests,
        "zero_failed": requests["failed"] == 0,
        "outputs": outputs,
        "byte_identical": outputs["corrupt"] == 0 and outputs["verified"] > 0,
        "migrations": {
            **migrations,
            "defrag_moves": len(moves),
            "per_phase": {
                name: (p.get("migrations") or {}).get("committed", 0)
                for name, p in by_name.items()
            },
        },
        "kv_occupancy_variance": variance,
        "phase_failures": {
            p["name"]: p["assertions"]["failures"]
            for p in phases if p["assertions"]["failures"]
        },
    }


def _control_spec(spec):
    """The SAME full scenario with only the defrag loop switched off — the
    A/B control for the occupancy-variance measurement.  All phases run so
    the defrag phase inherits identical fleet state (including the worker
    the drain phase removed); only the defrag phase's migration floor is
    relaxed (without the loop there is nothing to commit there)."""
    from dynamo_tpu.scenarios.spec import ScenarioSpec

    control = ScenarioSpec.from_dict(spec.to_dict())
    control.autopilot.defrag = False
    for phase in control.phases:
        if phase.name == "defrag":
            phase.assertions.min_migrations_committed = 0
    return control


async def amain(out: Path, speedup: float | None) -> int:
    from dynamo_tpu.robustness import counters
    from dynamo_tpu.robustness.faults import FAULTS
    from dynamo_tpu.scenarios.runner import run_scenario
    from dynamo_tpu.scenarios.spec import ScenarioSpec, builtin_spec_path

    counters.reset()
    FAULTS.reset()
    spec = ScenarioSpec.load(builtin_spec_path("migration"))
    if speedup is not None:
        spec.speedup = speedup
    artifact = await run_scenario(spec.validate(), name="migration-bench")
    counters.reset()
    FAULTS.reset()
    control = await run_scenario(
        _control_spec(spec).validate(), name="migration-bench-control"
    )
    record = summarize(artifact, control)
    out.write_text(json.dumps(record, indent=2) + "\n")
    print(json.dumps(record, indent=2))
    print(f"wrote {out}")
    return 0 if record["passed"] else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", type=Path, default=_REPO_ROOT / "MIGRATION_BENCH.json"
    )
    parser.add_argument(
        "--speedup", type=float, default=None,
        help="override the spec's simulation speedup",
    )
    args = parser.parse_args(argv)
    return asyncio.run(amain(args.out, args.speedup))


if __name__ == "__main__":
    sys.exit(main())
