#!/usr/bin/env python
"""Perf regression gate CLI over the committed benchmark artifacts.

The dynlint model, applied to performance: ``PERF_BASELINE.json`` commits
the accepted value of every headline metric the artifact pile carries
(schema: ``dynamo_tpu/bench/perfgate.py``); this gate fails on a NEW
regression (metric degraded beyond its tolerance band) and on a STALE
baseline entry (metric no longer extractable), so the baseline can only
ever be moved deliberately.

Usage::

    python scripts/perfgate.py                 # check (tier-1 runs this too)
    python scripts/perfgate.py --json          # machine-readable findings
    python scripts/perfgate.py --write-baseline  # re-record after a
                                                 # LEGITIMATE perf change

``--write-baseline`` refuses to run while any artifact has uncommitted
modifications — a baseline recorded over a dirty pile would launder
unreviewed numbers into the ratchet.  Commit (or revert) the artifacts
first; see docs/autopilot.md for the rebaseline process.

Exit code 0 = gate passes; 1 = findings (printed one per line).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from dynamo_tpu.bench import perfgate  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root", default=str(REPO_ROOT),
                        help="directory holding the artifact pile "
                             "(default: the repo root)")
    parser.add_argument("--baseline", default=None,
                        help="explicit PERF_BASELINE.json path (default: "
                             "DYN_PERFGATE_BASELINE or <root>/PERF_BASELINE.json)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON")
    parser.add_argument("--write-baseline", action="store_true",
                        help="re-record the baseline from the current pile "
                             "(refuses over a dirty artifact set)")
    args = parser.parse_args(argv)

    root = Path(args.root)
    if args.write_baseline:
        dirty = perfgate.dirty_artifacts(root)
        if dirty:
            print(
                "refusing --write-baseline: uncommitted artifact changes in "
                + ", ".join(dirty)
            )
            print("commit (or revert) the artifacts first, then re-record.")
            return 1
        try:
            out = perfgate.write_baseline(root, args.baseline)
        except ValueError as exc:
            print(exc)
            return 1
        print(f"baseline written to {out}")
        return 0

    baseline_file = (
        Path(args.baseline) if args.baseline else perfgate.baseline_path(root)
    )
    try:
        baseline = perfgate.load_baseline(baseline_file)
    except (OSError, ValueError) as exc:
        print(f"cannot load baseline {baseline_file}: {exc}")
        print("record one with: python scripts/perfgate.py --write-baseline")
        return 1
    findings = perfgate.check(root, baseline)
    if args.json:
        print(json.dumps(
            [{"kind": f.kind, "metric": f.metric, "detail": f.detail}
             for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        if not findings:
            values, _ = perfgate.extract_metrics(root)
            print(f"perf gate ok ({len(values)} metrics within band)")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
