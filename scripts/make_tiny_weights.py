"""Generate deterministic demo weights for tests/data/tiny-chat-model.

Random-initialized weights on a 106k-param model produce a DEGENERATE
serving demo: logits are near-one-hot on an arbitrary token (often a
special that detokenizes to ""), and since decode conditions only on the
last token the engine self-loops on it forever — `curl` against the
runnable examples streamed 8 empty deltas.

These weights make the tiny model a **token counter**: attention and MLP
outputs are zeroed (wo = w_down = 0, so the residual stream carries the
input embedding through unchanged), embeddings are random unit rows, and
the untied unembedding is the embedding table rolled by one row — so
logits after last token t peak sharply at t+1.  Every decode emits the
next token id: deterministic, visibly textful, and exactness-friendly
(disagg/parallel parity tests get bit-stable references).

Run from the repo root (rewrites model.safetensors in place):

    python scripts/make_tiny_weights.py
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

MODEL_DIR = Path(__file__).parent.parent / "tests" / "data" / "tiny-chat-model"
# sharpness of the one-hot logit peak; 8.0 gives a ~1e-12 runner-up after
# softmax yet keeps finite logprobs for the logprobs-surface tests
UNEMBED_SCALE = 8.0


def build_tensors() -> dict[str, np.ndarray]:
    cfg = json.loads((MODEL_DIR / "config.json").read_text())
    vocab, hidden = cfg["vocab_size"], cfg["hidden_size"]
    inter, layers = cfg["intermediate_size"], cfg["num_hidden_layers"]
    q_dim = cfg["num_attention_heads"] * cfg["head_dim"]
    kv_dim = cfg["num_key_value_heads"] * cfg["head_dim"]

    rng = np.random.default_rng(0)
    embed = rng.standard_normal((vocab, hidden)).astype(np.float32)
    embed /= np.linalg.norm(embed, axis=1, keepdims=True)

    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": embed,
        "model.norm.weight": np.ones(hidden, np.float32),
        # unembed row j = embedding of j-1: logits(last=t) peak at t+1
        "lm_head.weight": UNEMBED_SCALE * np.roll(embed, 1, axis=0),
    }
    for i in range(layers):
        p = f"model.layers.{i}"
        small = lambda *s: (  # noqa: E731
            rng.standard_normal(s).astype(np.float32) * 0.02
        )
        tensors.update({
            f"{p}.input_layernorm.weight": np.ones(hidden, np.float32),
            f"{p}.post_attention_layernorm.weight": np.ones(hidden, np.float32),
            f"{p}.self_attn.q_proj.weight": small(q_dim, hidden),
            f"{p}.self_attn.k_proj.weight": small(kv_dim, hidden),
            f"{p}.self_attn.v_proj.weight": small(kv_dim, hidden),
            # zero out the residual writes: the stream stays the embedding
            f"{p}.self_attn.o_proj.weight": np.zeros((hidden, q_dim), np.float32),
            f"{p}.mlp.gate_proj.weight": small(inter, hidden),
            f"{p}.mlp.up_proj.weight": small(inter, hidden),
            f"{p}.mlp.down_proj.weight": np.zeros((hidden, inter), np.float32),
        })
    return tensors


def main() -> None:
    from safetensors.numpy import save_file

    cfg_path = MODEL_DIR / "config.json"
    cfg = json.loads(cfg_path.read_text())
    if cfg.get("tie_word_embeddings"):
        # the counter needs an untied unembedding (a tied one's logit
        # profile <norm(e_t), e_j> is symmetric in j-t: it cannot prefer
        # t+1 over t-1)
        cfg["tie_word_embeddings"] = False
        cfg_path.write_text(json.dumps(cfg, indent=2) + "\n")
    save_file(build_tensors(), MODEL_DIR / "model.safetensors")
    print(f"wrote {MODEL_DIR / 'model.safetensors'}")


if __name__ == "__main__":
    main()
