#!/usr/bin/env python
"""Smoke-check the Prometheus surfaces of a running deployment.

Scrapes the frontend's ``/metrics`` (``dyn_llm_*`` + ``dyn_slo_*`` families)
and the metrics service's ``/metrics`` (``dyn_worker_*`` families) and asserts
every expected metric family is present AND none is declared twice — the fast
"is observability wired at all?" gate for CI and for operators bringing up a
fleet.

Usage::

    python scripts/check_metrics.py \
        --frontend http://127.0.0.1:8080/metrics \
        --worker   http://127.0.0.1:9091/metrics

Either URL may be omitted to check only one surface.  Exit code 0 = all
expected families present; 1 = something missing (printed).

The family lists are importable (``FRONTEND_FAMILIES``/``WORKER_FAMILIES``,
``missing_families``) so the tier-1 test (tests/llm/test_check_metrics.py)
runs the same assertions in-process without sockets flakiness.
"""

from __future__ import annotations

import argparse
import re
import sys
import urllib.request

# resilience counters (dynamo_tpu/robustness/counters.py): appended to the
# frontend's /metrics body and mirrored as gauges by the metrics service
RESILIENCE_FAMILIES = (
    "dyn_cp_reconnects_total",
    "dyn_retries_total",
    "dyn_shed_total",
    "dyn_faults_injected_total",
)

# mid-stream resume + graceful drain (dynamo_tpu/runtime/resume.py and the
# ingress drain state machine), exported next to the other resilience counters
RESUME_DRAIN_FAMILIES = (
    "dyn_resume_attempts_total",
    "dyn_resume_success_total",
    "dyn_resume_prefill_requeues_total",
    "dyn_drain_started_total",
    "dyn_drain_completed_total",
    "dyn_drain_handoff_total",
)

# live session migration (dynamo_tpu/runtime/migration.py), counted in the
# same robustness registry and rendered on both surfaces
MIGRATION_FAMILIES = (
    "dyn_migration_started_total",
    "dyn_migration_committed_total",
    "dyn_migration_aborted_total",
    "dyn_migration_failed_total",
    "dyn_migration_hidden_seconds",
)

# SLO burn-rate families (dynamo_tpu/observability/slo.py), appended to the
# frontend exposition next to the resilience counters
SLO_FAMILIES = (
    "dyn_slo_burn_rate_ratio",
    "dyn_slo_good_total",
    "dyn_slo_bad_total",
    "dyn_slo_threshold_seconds",
)

# perf flight recorder (dynamo_tpu/observability/flight.py): ring-buffer
# accounting rendered on BOTH surfaces — aggregated text families on the
# frontend (flight.render()) and per-worker gauges on the metrics service.
# Always declared — zeros until a recorder goes live.
FLIGHT_FAMILIES = (
    "dyn_flight_records_total",
    "dyn_flight_dropped_total",
    "dyn_flight_dumps_total",
    "dyn_flight_buffer_bytes",
)

# fleet topology plane (dynamo_tpu/topology/): map shape + link measurements,
# rendered on BOTH surfaces (frontend text helper + metrics-service registry).
# Always declared — zeros until topology cards are published.
TOPOLOGY_FAMILIES = (
    "dyn_topology_nodes",
    "dyn_topology_links",
    "dyn_topology_probe_rtt_seconds",
    "dyn_topology_probe_bandwidth_bps",
    "dyn_topology_map_age_seconds",
)

# frontend registry (dynamo_tpu/llm/http/metrics.py) + resilience counters
FRONTEND_FAMILIES = (
    "dyn_llm_http_service_requests_total",
    "dyn_llm_http_service_inflight_requests",
    "dyn_llm_http_service_request_duration_seconds",
    "dyn_llm_http_service_time_to_first_token_seconds",
    "dyn_llm_http_service_inter_token_latency_seconds",
    "dyn_llm_http_service_input_sequence_tokens",
    "dyn_llm_http_service_output_sequence_tokens",
) + RESILIENCE_FAMILIES + RESUME_DRAIN_FAMILIES + MIGRATION_FAMILIES + SLO_FAMILIES + TOPOLOGY_FAMILIES + FLIGHT_FAMILIES

# utilization accounting (dynamo_tpu/observability/perf.py → engine stats →
# ForwardPassMetrics → metrics service)
UTILIZATION_FAMILIES = (
    "dyn_worker_mfu_perc",
    "dyn_worker_bandwidth_util_perc",
    "dyn_worker_goodput_tokens_per_second",
    "dyn_worker_prefill_tokens_per_second",
    "dyn_worker_prefill_tokens",
    "dyn_worker_decode_tokens",
    "dyn_worker_tokens_emitted",
    "dyn_worker_preempted_tokens",
    "dyn_worker_spec_rejected_tokens",
    "dyn_worker_wasted_tokens",
    "dyn_worker_engine_phase_seconds",
)

# predictive prefetch (dynamo_tpu/prefetch/ via engine stats) + offload-tier
# occupancy, mirrored by the metrics service
PREFETCH_FAMILIES = (
    "dyn_prefetch_hits_total",
    "dyn_prefetch_misses_total",
    "dyn_prefetch_stale_total",
    "dyn_prefetch_hidden_seconds",
    "dyn_worker_offload_blocks",
    "dyn_worker_offload_blocks_used",
    "dyn_worker_offload_blocks_pinned",
)

# disagg streamed KV transfer (dynamo_tpu/llm/disagg.py via engine stats →
# ForwardPassMetrics → metrics service): routing outcomes, transfer totals,
# and the hidden-fraction headline
DISAGG_FAMILIES = (
    "dyn_disagg_remote_prefills_total",
    "dyn_disagg_local_prefills_total",
    "dyn_disagg_prefill_timeouts_total",
    "dyn_disagg_kv_transfer_bytes_total",
    "dyn_disagg_kv_transfer_seconds_total",
    "dyn_disagg_kv_transfer_hidden_seconds_total",
    "dyn_disagg_kv_transfer_parts_total",
    "dyn_disagg_transfer_hidden_ratio",
    "dyn_disagg_kv_transfer_bandwidth_bps",
)

# ragged unified-batch step (engine unified_batch knob → engine stats →
# ForwardPassMetrics → metrics service)
UNIFIED_FAMILIES = (
    "dyn_worker_unified_windows",
    "dyn_worker_admission_drains",
    "dyn_worker_unified_fallbacks_total",
)

# planner autopilot state (dynamo_tpu/planner/state.py events mirrored by
# the metrics service): latest decision targets + the burn input behind them
PLANNER_FAMILIES = (
    "dyn_planner_target_replicas",
    "dyn_planner_observed_capacity_tok_s",
    "dyn_planner_burn_rate_input",
)

# metrics service registry (dynamo_tpu/components/metrics_service.py)
WORKER_FAMILIES = (
    "dyn_worker_kv_active_blocks",
    "dyn_worker_kv_total_blocks",
    "dyn_worker_cache_usage_perc",
    "dyn_worker_requests_waiting",
    "dyn_worker_requests_running",
    "dyn_worker_batch_occupancy_perc",
    "dyn_worker_preemptions",
    "dyn_worker_prefix_hits",
    "dyn_worker_prefix_cached_tokens",
    "dyn_worker_spec_accepted_tokens",
    "dyn_worker_kv_hit_blocks_total",
    "dyn_worker_kv_isl_blocks_total",
) + UNIFIED_FAMILIES + UTILIZATION_FAMILIES + RESILIENCE_FAMILIES + RESUME_DRAIN_FAMILIES + MIGRATION_FAMILIES + PREFETCH_FAMILIES + PLANNER_FAMILIES + DISAGG_FAMILIES + TOPOLOGY_FAMILIES + FLIGHT_FAMILIES + (
    # worker-surface-only: per-worker placement facts for dyn_top, plus the
    # latest flight-dump reason per worker (info-gauge, value 1)
    "dyn_topology_worker_info",
    "dyn_flight_last_dump_info",
)

_HELP_RE = re.compile(r"^# (?:HELP|TYPE) (\S+)", re.MULTILINE)
_TYPE_RE = re.compile(r"^# TYPE (\S+)", re.MULTILINE)


def exposed_families(text: str) -> set[str]:
    """Metric family names declared in a Prometheus text exposition."""
    return set(_HELP_RE.findall(text))


def missing_families(text: str, expected) -> list[str]:
    have = exposed_families(text)
    return [name for name in expected if name not in have]


def duplicate_families(text: str) -> list[str]:
    """Families declared (``# TYPE``) more than once — the signature of two
    code paths registering the same metric, which Prometheus servers reject
    and dashboards silently double-count."""
    counts: dict[str, int] = {}
    for name in _TYPE_RE.findall(text):
        counts[name] = counts.get(name, 0) + 1
    return sorted(name for name, n in counts.items() if n > 1)


def _scrape(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
        return resp.read().decode("utf-8", "replace")


def check_url(url: str, expected, timeout: float = 5.0) -> tuple[list[str], list[str]]:
    """(missing families, duplicated families) for a live endpoint."""
    text = _scrape(url, timeout)
    return missing_families(text, expected), duplicate_families(text)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--frontend", help="frontend /metrics URL (dyn_llm_*)")
    parser.add_argument("--worker", help="metrics service /metrics URL (dyn_worker_*)")
    parser.add_argument("--timeout", type=float, default=5.0)
    args = parser.parse_args(argv)
    if not args.frontend and not args.worker:
        parser.error("give --frontend and/or --worker")

    failed = False
    for url, expected, label in (
        (args.frontend, FRONTEND_FAMILIES, "frontend"),
        (args.worker, WORKER_FAMILIES, "worker"),
    ):
        if not url:
            continue
        try:
            missing, duplicated = check_url(url, expected, args.timeout)
        except OSError as exc:
            print(f"{label}: scrape of {url} failed: {exc}")
            failed = True
            continue
        if missing:
            print(f"{label}: {url} missing families: {', '.join(missing)}")
            failed = True
        if duplicated:
            print(f"{label}: {url} duplicate families: {', '.join(duplicated)}")
            failed = True
        if not missing and not duplicated:
            print(f"{label}: {url} ok ({len(expected)} families)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
