"""Generate the self-contained test model artifacts in tests/data/tiny-chat-model/.

Trains a tiny byte-level BPE tokenizer on a synthetic corpus and writes a
llama-style chat template.  Run once; artifacts are committed so tests are
deterministic and need no network (the reference bundles HF checkouts under
lib/llm/tests/data/sample-models for the same reason; ours are generated, not
copied).
"""

from __future__ import annotations

import json
import random
from pathlib import Path

from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

OUT = Path(__file__).parent.parent / "tests" / "data" / "tiny-chat-model"

SPECIALS = ["<|bos|>", "<|eos|>", "<|sys|>", "<|user|>", "<|asst|>", "<|end|>", "<|pad|>"]

CHAT_TEMPLATE = (
    "{{ '<|bos|>' }}"
    "{% for message in messages %}"
    "{% if message.role == 'system' %}{{ '<|sys|>' + message.content + '<|end|>' }}"
    "{% elif message.role == 'user' %}{{ '<|user|>' + message.content + '<|end|>' }}"
    "{% elif message.role == 'assistant' %}{{ '<|asst|>' + message.content + '<|end|>' }}"
    "{% endif %}"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|asst|>' }}{% endif %}"
)


def synthetic_corpus() -> list[str]:
    rng = random.Random(1337)
    words = [
        "the", "quick", "brown", "fox", "jumps", "over", "lazy", "dog", "hello",
        "world", "token", "stream", "model", "tensor", "shard", "mesh", "cache",
        "block", "prefill", "decode", "route", "batch", "attention", "kernel",
        "memory", "device", "python", "compile", "llama", "matrix", "vector",
        "zero", "one", "two", "three", "four", "alpha", "beta", "gamma", "delta",
    ]
    lines = []
    for _ in range(3000):
        n = rng.randint(3, 14)
        lines.append(" ".join(rng.choice(words) for _ in range(n)) + ".")
    # unicode coverage so multi-byte decode paths are exercised
    lines += ["héllo wörld 你好世界 🚀 émoji ñandú çava"] * 50
    return lines


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    tokenizer = Tokenizer(models.BPE(unk_token=None))
    tokenizer.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tokenizer.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=2048,
        special_tokens=SPECIALS,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tokenizer.train_from_iterator(synthetic_corpus(), trainer)
    tokenizer.save(str(OUT / "tokenizer.json"))

    (OUT / "tokenizer_config.json").write_text(
        json.dumps(
            {
                "model_type": "llama",
                "bos_token": "<|bos|>",
                "eos_token": "<|eos|>",
                "pad_token": "<|pad|>",
                "chat_template": CHAT_TEMPLATE,
                "model_max_length": 2048,
            },
            indent=2,
        )
    )
    # minimal config.json (tiny llama-class geometry for engine tests)
    (OUT / "config.json").write_text(
        json.dumps(
            {
                "model_type": "llama",
                "vocab_size": tokenizer.get_vocab_size(),
                "hidden_size": 64,
                "intermediate_size": 128,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
                "num_key_value_heads": 2,
                "head_dim": 16,
                "max_position_embeddings": 2048,
                "rms_norm_eps": 1e-5,
                "rope_theta": 10000.0,
                "bos_token_id": 0,
                "eos_token_id": 1,
                "tie_word_embeddings": True,
            },
            indent=2,
        )
    )
    print(f"wrote artifacts to {OUT}, vocab={tokenizer.get_vocab_size()}")


if __name__ == "__main__":
    main()
