"""One live TPU hour → every on-device artifact, in priority order.

The axon tunnel has been dead for whole rounds and can wedge again at any
moment, so when it IS alive the evidence must land in a fixed, most-
valuable-first order, each stage checkpointed to disk before the next
starts:

1. probe       — relay socket + jax.devices() (seconds; abort early if dead)
2. kernels     — scripts/tpu_validate.py, compile/parity for every Pallas
                 kernel with real Mosaic (the round-3 lesson: interpret-mode
                 success proves nothing about lowering)
3. kernel perf — scripts/tpu_validate.py --bench → KERNEL_PERF.json with
                 platform=tpu, activating attention_impl="auto"'s measured
                 per-shape selection (engine/engine.py) AND the autotune
                 stage: wall-clock sweep of the ragged kernel's
                 (tb_tokens, page_slots, pages_per_step) grid whose
                 measured winners the engine resolves at init
                 (ops/autotune.py)
4. decode prof — scripts/profile_decode.py → PROFILE_DECODE.json, the
                 steady-state hot-loop phase split (schedule/upload/
                 dispatch/readback/post) that located the cross-backend
                 re-staging bug
5. bench       — bench.py headline ladder (llama3_8b int8, ISL 3000 /
                 OSL 150) → BENCH JSON with platform=tpu, real MFU,
                 vs_baseline vs the 145 tok/s/GPU reference figure
6. disagg      — dynamo_tpu.bench.disagg_bench → DISAGG_BENCH.json,
                 req/s + decode-phase tok/s through the full disagg path
                 (remote prefill, KV transfer, landing) vs aggregated
7. fleet       — routed-fleet KV-routing artifact with REAL engines on the
                 chip (ROUTED_FLEET_JAX.json; the mocker artifact stays as
                 the reference-style sim)

Run:  python scripts/tpu_roundup.py [--skip-fleet] [--budget-min 50]

Every stage writes its artifact even if later stages die; rerunning skips
nothing (artifacts are cheap to refresh once compiles are cached in
.jax_cache).

Stage timeouts are generous on purpose: killing a process that holds the
device mid-compile/mid-execute can WEDGE the tunnel for hours (observed
round 5 — jax.devices() then hangs for every process).  Prefer waiting
out a slow stage over killing it.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe_relay(port: int = 2024, timeout: float = 5.0) -> str:
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return "n/a"
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    except OSError:
        return "refused"
    try:
        s.settimeout(3.0)
        try:
            data = s.recv(1)
        except socket.timeout:
            return "held_open"
        return "accept_then_close" if data == b"" else "data"
    finally:
        s.close()


def probe_devices(timeout_s: float = 120.0) -> bool:
    code = "import jax; print('OK', [d.platform for d in jax.devices()])"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=timeout_s,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        print("roundup: jax.devices() timed out — tunnel wedged", flush=True)
        return False
    out = proc.stdout.decode(errors="replace")
    print(f"roundup: device probe: {out.strip()[:200]}", flush=True)
    return "OK" in out and "tpu" in out


def run_stage(name: str, cmd: list[str], timeout_s: float) -> bool:
    print(f"roundup: === {name}: {' '.join(cmd)}", flush=True)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, cwd=REPO, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"roundup: {name} TIMED OUT after {timeout_s:.0f}s", flush=True)
        return False
    print(
        f"roundup: {name} rc={proc.returncode} in {time.monotonic()-t0:.0f}s",
        flush=True,
    )
    return proc.returncode == 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--skip-fleet", action="store_true")
    parser.add_argument("--budget-min", type=float, default=50.0,
                        help="total wall budget; later stages are skipped "
                        "when exceeded")
    args = parser.parse_args()
    t_start = time.monotonic()

    def remaining() -> float:
        return args.budget_min * 60 - (time.monotonic() - t_start)

    # The socket state is logged evidence only — a relay that closes a bare
    # probe connection can still serve the PJRT handshake (observed round 5).
    # probe_devices() is authoritative and bounded by its own timeout.
    state = probe_relay()
    print(f"roundup: relay state: {state}", flush=True)
    if not probe_devices():
        return 2

    results = {}
    results["kernels"] = run_stage(
        "kernels", [sys.executable, "scripts/tpu_validate.py"],
        min(900, remaining()),
    )
    results["kernel_perf"] = run_stage(
        "kernel_perf",
        [sys.executable, "scripts/tpu_validate.py", "--bench",
         "--out", "KERNEL_PERF.json"],
        min(1200, remaining()),
    )
    results["decode_profile"] = run_stage(
        "decode_profile",
        [sys.executable, "scripts/profile_decode.py", "--model", "llama32_1b",
         "--decode-steps", "8", "--out", "PROFILE_DECODE.json"],
        min(1500, remaining()),
    )
    results["bench"] = run_stage(
        "bench", [sys.executable, "bench.py"], min(2400, max(60, remaining())),
    )
    if remaining() > 300:
        results["disagg_bench"] = run_stage(
            "disagg_bench",
            [sys.executable, "-m", "dynamo_tpu.bench.disagg_bench"],
            min(1800, remaining()),
        )
    if not args.skip_fleet and remaining() > 300:
        results["fleet_jax"] = run_stage(
            "fleet_jax",
            [sys.executable, "-m", "dynamo_tpu.bench.routed_fleet",
             "--engine", "jax", "--num-sessions", "16", "--turns", "3"],
            min(1200, remaining()),
        )
    print("roundup: " + json.dumps(results), flush=True)
    return 0 if all(results.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
