#!/usr/bin/env python
"""Chaos smoke: serve on CPU under a canned fault schedule, assert recovery.

Brings up the full serving stack in one process — dynctl control-plane
server, two echo workers, HTTP frontend with tight admission control — then:

1. arms a fault schedule (``DYN_FAULTS`` env if set, else the schedule from
   the canned scenario spec ``dynamo_tpu/scenarios/specs/chaos_smoke.json``
   — kill the control-plane connection once and one worker stream
   pre-first-token);
2. runs a multi-request serve phase and asserts **every** request completed
   (reconnect + safe retry both observable:
   ``dyn_cp_reconnects_total >= 1``, ``dyn_retries_total >= 1``);
3. fires a saturation burst and asserts overload surfaces as 429/503 with
   ``Retry-After`` (``dyn_shed_total >= 1``) instead of timeouts;
4. kills a worker stream **mid-decode** (``dp.send:nth=4``) and asserts the
   dispatcher's generation journal resumed it on the peer with zero
   client-visible failures (``dyn_resume_success_total >= 1``);
5. live-migrates a mid-decode stream to the peer worker and asserts the
   client saw a byte-identical stream (``dyn_migration_committed_total >=
   1``), then injects a destination death mid-handoff
   (``migrate.handoff:once``) and asserts the migration aborted back to
   the source with the stream still completing byte-identically
   (``dyn_migration_aborted_total`` moved, exactly-once either way);
6. gracefully drains one worker and asserts it deregistered (instance gone
   from the control-plane view) while the survivor keeps serving 200s.

Exit code 0 = recovered; 1 = a request failed or a recovery counter stayed
flat (printed).  Runs in tier-1 via tests/robustness/test_chaos_smoke.py.

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_smoke.py [--requests 6] [--burst 20]
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).parent.parent
if str(_REPO_ROOT) not in sys.path:  # standalone runs (tests import us
    sys.path.insert(0, str(_REPO_ROOT))  # with the root already on path)

from dynamo_tpu.utils import knobs  # noqa: E402  (needs the path bootstrap)

MODEL_DIR = str(_REPO_ROOT / "tests" / "data" / "tiny-chat-model")
# last-resort fallback if the shipped spec file is missing/unreadable
_FALLBACK_SCHEDULE = "cp.recv:once;worker.generate:nth=2"


def _canned() -> tuple[int, int, str]:
    """(requests, burst, schedule) from the shipped scenario spec — the
    canned chaos phases live in specs/chaos_smoke.json, not in code."""
    try:
        from dynamo_tpu.scenarios.spec import ScenarioSpec, builtin_spec_path

        spec = ScenarioSpec.load(builtin_spec_path("chaos_smoke"))
        serve, burst = spec.phases[0], spec.phases[1]
        return (
            serve.traffic.requests or 6,
            burst.traffic.requests or 20,
            serve.faults[0].schedule if serve.faults else _FALLBACK_SCHEDULE,
        )
    except Exception:  # noqa: BLE001 — the gate must run even if the spec rots
        return 6, 20, _FALLBACK_SCHEDULE


DEFAULT_SCHEDULE = _canned()[2]


async def _chat(client, i: int) -> int:
    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "tiny",
            "messages": [{"role": "user", "content": f"chaos request {i}"}],
            "max_tokens": 8,
        },
        timeout=60,
    )
    return r.status_code


async def amain(
    requests: int | None = None, burst: int | None = None,
    schedule: str | None = None,
) -> int:
    import json as _json
    import os

    import httpx

    from dynamo_tpu.robustness import AdmissionConfig, counters
    from dynamo_tpu.robustness.faults import FAULTS
    from dynamo_tpu.runtime.controlplane.server import ControlPlaneServer
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.serve import serve_frontend, serve_worker
    from dynamo_tpu.utils.config import RuntimeConfig

    spec_requests, spec_burst, spec_schedule = _canned()
    requests = spec_requests if requests is None else requests
    burst = spec_burst if burst is None else burst
    schedule = schedule or knobs.get("DYN_FAULTS") or spec_schedule
    # a DYN_FAULTS env schedule is armed at import — disarm it for bring-up
    # (the schedule targets the serve phase; cp.recv:once firing on the
    # connect handshake would fail setup, not test recovery) and start the
    # recovery counters from zero so the assertions below are absolute
    FAULTS.reset()
    counters.reset()
    failures: list[str] = []

    def check(ok: bool, what: str) -> None:
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    cp = ControlPlaneServer(port=0)
    await cp.start()
    runtime = await DistributedRuntime.create(
        RuntimeConfig(control_plane=f"127.0.0.1:{cp.port}")
    )
    workers, service, watcher = [], None, None
    try:
        for _ in range(2):
            workers.append(
                await serve_worker(runtime, MODEL_DIR, model_name="tiny", engine_kind="echo")
            )
        service, watcher = await serve_frontend(
            runtime, host="127.0.0.1", port=0,
            admission=AdmissionConfig(
                max_inflight=1, max_queue_depth=2,
                queue_timeout_s=10.0, retry_after_s=1.0,
            ),
        )
        async with httpx.AsyncClient(
            base_url=f"http://127.0.0.1:{service.port}",
            limits=httpx.Limits(max_connections=64),
        ) as client:
            for _ in range(100):
                r = await client.get("/v1/models")
                if any(m["id"] == "tiny" for m in r.json().get("data", [])):
                    break
                await asyncio.sleep(0.1)

            # arm only once the stack is up: the schedule targets the serve
            # phase, not worker bring-up.  reset() first — a DYN_FAULTS env
            # schedule was already armed at import, and arming it again
            # here would double every spec (nth fires twice, etc.)
            FAULTS.reset()
            FAULTS.arm(schedule)
            print(f"armed fault schedule: {schedule}")

            # phase 1 — every request must complete despite the faults
            statuses = [await _chat(client, i) for i in range(requests)]
            check(
                all(s == 200 for s in statuses),
                f"serve phase: {statuses.count(200)}/{requests} requests ok "
                f"(statuses {sorted(set(statuses))})",
            )
            check(
                counters.get("dyn_cp_reconnects_total") >= 1,
                f"control-plane reconnected (dyn_cp_reconnects_total="
                f"{counters.get('dyn_cp_reconnects_total')})",
            )
            check(
                counters.get("dyn_retries_total") >= 1,
                f"pre-first-token retry happened (dyn_retries_total="
                f"{counters.get('dyn_retries_total')})",
            )

            # phase 2 — saturation burst: overload must shed, not time out
            responses = await asyncio.gather(
                *[
                    client.post(
                        "/v1/chat/completions",
                        json={
                            "model": "tiny",
                            "messages": [{"role": "user", "content": "burst"}],
                            "max_tokens": 4,
                        },
                        timeout=60,
                    )
                    for _ in range(burst)
                ]
            )
            codes = [r.status_code for r in responses]
            shed = [r for r in responses if r.status_code in (429, 503)]
            check(
                all(c in (200, 429, 503) for c in codes),
                f"burst: only 200/429/503 (saw {sorted(set(codes))})",
            )
            check(len(shed) >= 1, f"burst shed {len(shed)}/{burst} requests")
            check(
                all("retry-after" in r.headers for r in shed),
                "every shed response carries Retry-After",
            )
            check(
                counters.get("dyn_shed_total") >= len(shed),
                f"dyn_shed_total={counters.get('dyn_shed_total')}",
            )

            # the counters are on the scrape surface too
            r = await client.get("/metrics")
            check(
                "dyn_cp_reconnects_total" in r.text and "dyn_shed_total" in r.text,
                "resilience counters exported on /metrics",
            )

            # phase 3 — worker kill mid-decode: the 4th mid-stream write
            # dies AFTER tokens reached the client; the dispatcher's
            # generation journal must resume the stream on the peer with
            # exactly-once delivery (no client-visible failure)
            FAULTS.reset()
            FAULTS.arm("dp.send:nth=4")
            resumes_before = counters.get("dyn_resume_success_total")
            statuses = [await _chat(client, 100 + i) for i in range(3)]
            check(
                all(s == 200 for s in statuses),
                f"worker-kill phase: {statuses.count(200)}/3 requests ok "
                f"(statuses {sorted(set(statuses))})",
            )
            check(
                counters.get("dyn_resume_success_total") >= resumes_before + 1,
                f"mid-stream resume happened (dyn_resume_success_total="
                f"{counters.get('dyn_resume_success_total')})",
            )

            # phase 5 — live migration: move a mid-decode stream to the
            # peer worker (client must see a byte-identical stream), then
            # kill the destination mid-handoff and assert the migration
            # aborts cleanly back to the source (exactly-once either way)
            FAULTS.reset()
            for w in workers:
                # echo streams are instant by default; pace them so a
                # stream is still live long enough to migrate mid-decode
                w.engine.token_delay_s = 0.03
            pipelines = getattr(watcher, "_pipelines", {})
            mig = next(
                (
                    p["router"].migrations
                    for p in pipelines.values()
                    if p.get("router") is not None
                    and p["router"].migrations is not None
                ),
                None,
            )
            check(mig is not None, "migration coordinator on the frontend router")
            if mig is not None:
                long_prompt = "migrate " * 120

                async def _stream_chat() -> tuple[int, str]:
                    text: list[str] = []
                    async with client.stream(
                        "POST", "/v1/chat/completions",
                        json={
                            "model": "tiny",
                            "messages": [
                                {"role": "user", "content": long_prompt}
                            ],
                            "max_tokens": 64, "stream": True,
                        },
                        timeout=60,
                    ) as r:
                        status = r.status_code
                        async for line in r.aiter_lines():
                            if not line.startswith("data:") or line.endswith(
                                "[DONE]"
                            ):
                                continue
                            chunk = _json.loads(line[5:])
                            for c in chunk.get("choices", []):
                                text.append(
                                    (c.get("delta") or {}).get("content") or ""
                                )
                    return status, "".join(text)

                async def _migrate_first_session() -> dict | None:
                    for _ in range(300):
                        sessions = mig.sessions()
                        if sessions:
                            rid = sorted(sessions)[0]
                            return await mig.migrate(rid, reason="manual")
                        await asyncio.sleep(0.01)
                    return None

                # unmigrated run fixes the exactly-once reference text
                status, reference = await _stream_chat()
                check(
                    status == 200 and bool(reference),
                    "migration baseline stream ok",
                )

                task = asyncio.ensure_future(_stream_chat())
                result = await _migrate_first_session()
                status, text = await task
                check(
                    bool(result and result.get("ok")),
                    f"live migration committed: {result}",
                )
                check(
                    status == 200 and text == reference,
                    "migrated stream byte-identical to the unmigrated baseline",
                )
                check(
                    counters.get("dyn_migration_committed_total") >= 1,
                    f"dyn_migration_committed_total="
                    f"{counters.get('dyn_migration_committed_total')}",
                )

                # destination death mid-handoff: abort, finish on the source
                FAULTS.arm("migrate.handoff:once")
                aborts_before = counters.get("dyn_migration_aborted_total")
                task = asyncio.ensure_future(_stream_chat())
                result = await _migrate_first_session()
                status, text = await task
                check(
                    bool(result) and not result.get("ok"),
                    f"fault-injected migration aborted: {result}",
                )
                check(
                    counters.get("dyn_migration_aborted_total")
                    >= aborts_before + 1,
                    f"dyn_migration_aborted_total="
                    f"{counters.get('dyn_migration_aborted_total')}",
                )
                check(
                    status == 200 and text == reference,
                    "aborted-migration stream completed on the source, "
                    "byte-identical",
                )
            for w in workers:
                w.engine.token_delay_s = 0.0

            # phase 6 — graceful drain: one worker empties and deregisters;
            # the survivor keeps serving with zero 5xx
            FAULTS.reset()
            from dynamo_tpu.runtime.component import ROOT_PATH

            drained = workers[-1]
            drained_id = drained.service.instance.instance_id
            result = await drained.drain()
            check(bool(result.get("ok")), f"drain completed: {result}")
            gone = not any(
                "/instances/" in e.key
                and _json.loads(e.value)["instance_id"] == drained_id
                for e in await runtime.plane.kv.get_prefix(ROOT_PATH)
            )
            check(gone, "drained instance deregistered from control plane")
            statuses = [await _chat(client, 200 + i) for i in range(3)]
            check(
                all(s == 200 for s in statuses),
                f"post-drain: {statuses.count(200)}/3 requests ok on survivor "
                f"(statuses {sorted(set(statuses))})",
            )
    finally:
        if watcher is not None:
            await watcher.stop()
        if service is not None:
            await service.stop()
        for w in workers:
            await w.shutdown()
        await runtime.close()
        await cp.stop()

    if failures:
        print(f"chaos smoke FAILED ({len(failures)} check(s))")
        return 1
    print("chaos smoke passed")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--requests", type=int, default=None,
                        help="serve-phase request count (default: from spec)")
    parser.add_argument("--burst", type=int, default=None,
                        help="burst size (default: from spec)")
    parser.add_argument("--faults", help=f"fault schedule (default {DEFAULT_SCHEDULE})")
    args = parser.parse_args(argv)
    return asyncio.run(amain(args.requests, args.burst, args.faults))


if __name__ == "__main__":
    sys.exit(main())
