#!/usr/bin/env python
"""dyn_top: a live ``top`` for a dynamo-tpu fleet.

Polls the metrics service (per-worker ``dyn_worker_*`` gauges) and the HTTP
frontend (``/metrics`` + ``/slo``) and renders one screen: per-worker MFU /
bandwidth utilization / goodput / KV usage / queue depth, fleet aggregates,
frontend in-flight + SLO burn rates.

Usage::

    python scripts/dyn_top.py \
        --frontend http://127.0.0.1:8080 \
        --worker   http://127.0.0.1:9091 \
        [--interval 2] [--once] [--json]

Either base URL may be omitted to watch one surface.  ``--once`` renders a
single frame and exits; ``--json`` emits the snapshot as JSON instead of a
table (``--once --json`` is the machine mode used by tier-1 tests and
benches).  stdlib only — usable on any node that can reach the endpoints.

``--flight`` switches to the flight-recorder tail: print the newest
``flight-*.jsonl`` dump (``--once``) or follow new dumps as they land
(default).  ``--flight-dir`` overrides the dump directory.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

_METRIC_LINE_HEAD = ("#",)

# dyn_worker_* gauge → snapshot key (per-worker table columns)
WORKER_FIELDS = {
    "dyn_worker_mfu_perc": "mfu_perc",
    "dyn_worker_bandwidth_util_perc": "bandwidth_util_perc",
    "dyn_worker_goodput_tokens_per_second": "goodput_tokens_per_second",
    "dyn_worker_cache_usage_perc": "kv_usage_perc",
    "dyn_worker_kv_active_blocks": "kv_active_blocks",
    "dyn_worker_requests_running": "running",
    "dyn_worker_requests_waiting": "waiting",
    "dyn_worker_batch_occupancy_perc": "batch_occupancy_perc",
    "dyn_worker_preemptions": "preemptions",
    "dyn_worker_unified_windows": "unified_windows",
    "dyn_worker_admission_drains": "admission_drains",
    "dyn_worker_prefill_tokens": "prefill_tokens",
    "dyn_worker_decode_tokens": "decode_tokens",
    "dyn_worker_tokens_emitted": "tokens_emitted",
    "dyn_worker_wasted_tokens": "wasted_tokens",
    "dyn_prefetch_hits_total": "prefetch_hits",
    "dyn_prefetch_misses_total": "prefetch_misses",
    "dyn_prefetch_stale_total": "prefetch_stale",
    "dyn_prefetch_hidden_seconds": "prefetch_hidden_seconds",
    "dyn_disagg_remote_prefills_total": "disagg_remote_prefills",
    "dyn_disagg_kv_transfer_parts_total": "disagg_kv_transfer_parts",
    "dyn_disagg_transfer_hidden_ratio": "disagg_transfer_hidden_ratio",
    "dyn_flight_records_total": "flight_records",
    "dyn_flight_dropped_total": "flight_dropped",
    "dyn_flight_dumps_total": "flight_dumps",
    "dyn_flight_buffer_bytes": "flight_buffer_bytes",
}

# offload-tier occupancy gauges carry a second label (tier) and nest under
# workers[wid]["offload_tiers"][tier]
TIER_FIELDS = {
    "dyn_worker_offload_blocks": "blocks",
    "dyn_worker_offload_blocks_used": "used",
    "dyn_worker_offload_blocks_pinned": "pinned",
}

# planner autopilot gauges (labeled by pool, not worker): latest decision
# targets and observed per-replica capacity, nested under snap["planner"]
PLANNER_FIELDS = {
    "dyn_planner_target_replicas": "target_replicas",
    "dyn_planner_observed_capacity_tok_s": "observed_capacity_tok_s",
}

# topology-plane placement info (value always 1; the facts ride as labels):
# slice label + inbound hop class per worker → the SLICE/HOP column
TOPOLOGY_INFO_FAMILY = "dyn_topology_worker_info"

# flight-recorder last-dump info (value always 1; the reason rides as a
# label) → the FLIGHT column's dump annotation
FLIGHT_INFO_FAMILY = "dyn_flight_last_dump_info"


def parse_prometheus(text: str) -> list[tuple[str, dict, float]]:
    """Minimal text-exposition parser: (family, labels, value) samples."""
    out: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith(_METRIC_LINE_HEAD):
            continue
        try:
            metric, value_str = line.rsplit(" ", 1)
            value = float(value_str)
        except ValueError:
            continue
        labels: dict[str, str] = {}
        name = metric
        if "{" in metric and metric.endswith("}"):
            name, _, label_body = metric.partition("{")
            for pair in label_body[:-1].split(","):
                if "=" not in pair:
                    continue
                k, _, v = pair.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        out.append((name, labels, value))
    return out


def _fetch(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
        return resp.read().decode("utf-8", "replace")


def collect_snapshot(
    frontend: str | None = None,
    worker: str | None = None,
    timeout: float = 5.0,
) -> dict:
    """One fleet snapshot (the ``--json`` payload).  Unreachable surfaces
    degrade to an ``error`` field rather than failing the whole frame —
    a top must keep rendering while half the fleet restarts."""
    snap: dict = {"ts": time.time(), "workers": {}, "fleet": {}, "frontend": {}}

    if worker:
        try:
            samples = parse_prometheus(_fetch(worker.rstrip("/") + "/metrics", timeout))
        except (OSError, urllib.error.URLError) as exc:
            snap["workers_error"] = str(exc)
            samples = []
        workers: dict[str, dict] = {}
        planner: dict = {}
        for name, labels, value in samples:
            if name == "dyn_planner_burn_rate_input":
                planner["burn_rate_input"] = value
                continue
            pkey = PLANNER_FIELDS.get(name)
            if pkey is not None and "pool" in labels:
                planner.setdefault("pools", {}).setdefault(
                    labels["pool"], {}
                )[pkey] = value
                continue
            if "worker" not in labels:
                continue
            if name == TOPOLOGY_INFO_FAMILY:
                row = workers.setdefault(labels["worker"], {})
                row["slice"] = labels.get("slice", "-")
                row["hop"] = labels.get("hop", "-")
                continue
            if name == FLIGHT_INFO_FAMILY:
                row = workers.setdefault(labels["worker"], {})
                row["flight_last_dump_reason"] = labels.get("reason", "-")
                continue
            tier_key = TIER_FIELDS.get(name)
            if tier_key is not None and "tier" in labels:
                row = workers.setdefault(labels["worker"], {})
                row.setdefault("offload_tiers", {}).setdefault(
                    labels["tier"], {}
                )[tier_key] = value
                continue
            key = WORKER_FIELDS.get(name)
            if key is None:
                continue
            workers.setdefault(labels["worker"], {})[key] = value
        for row in workers.values():
            judged = row.get("prefetch_hits", 0.0) + row.get("prefetch_misses", 0.0)
            if judged:
                row["prefetch_hit_ratio"] = row.get("prefetch_hits", 0.0) / judged
        snap["workers"] = workers
        if planner:
            snap["planner"] = planner
        if workers:
            rows = list(workers.values())
            snap["fleet"] = {
                "workers": len(rows),
                "goodput_tokens_per_second": sum(
                    r.get("goodput_tokens_per_second", 0.0) for r in rows
                ),
                "mfu_perc_avg": sum(r.get("mfu_perc", 0.0) for r in rows) / len(rows),
                "kv_usage_perc_avg": sum(
                    r.get("kv_usage_perc", 0.0) for r in rows
                ) / len(rows),
                "waiting": sum(r.get("waiting", 0.0) for r in rows),
                "running": sum(r.get("running", 0.0) for r in rows),
            }

    if frontend:
        base = frontend.rstrip("/")
        front: dict = {}
        try:
            samples = parse_prometheus(_fetch(base + "/metrics", timeout))
            front["inflight"] = sum(
                v for n, _l, v in samples
                if n == "dyn_llm_http_service_inflight_requests"
            )
            front["requests_total"] = sum(
                v for n, _l, v in samples
                if n == "dyn_llm_http_service_requests_total"
            )
            front["shed_total"] = sum(
                v for n, _l, v in samples if n == "dyn_shed_total"
            )
            # live-migration activity rides the frontend's counter surface
            # (the coordinator lives in the frontend's push router)
            front["migrations_committed"] = sum(
                v for n, _l, v in samples
                if n == "dyn_migration_committed_total"
            )
            front["migrations_aborted"] = sum(
                v for n, _l, v in samples
                if n == "dyn_migration_aborted_total"
            )
        except (OSError, urllib.error.URLError) as exc:
            front["error"] = str(exc)
        try:
            front["slo"] = json.loads(_fetch(base + "/slo", timeout))
        except (OSError, urllib.error.URLError, ValueError) as exc:
            # /metrics answering but /slo down is a degraded frontend, not a
            # dead one — keep the keys distinct so --once can tell them apart
            front["slo_error"] = str(exc)
        snap["frontend"] = front

    return snap


# -- flight-dump tailing -----------------------------------------------------
def flight_dump_dir(override: str | None = None) -> Path:
    """Where the flight recorder writes its JSONL dumps.  Mirrors
    dynamo_tpu.observability.flight.flight_dir() — duplicated so dyn_top
    stays stdlib-only and usable on nodes without the package installed."""
    if override:
        return Path(override)
    env = os.environ.get("DYN_FLIGHT_DIR")  # dynlint: disable=knob-registry -- stdlib-only tool, no package import
    if env:
        return Path(env)
    cache = os.environ.get("DYN_CACHE_DIR")  # dynlint: disable=knob-registry -- stdlib-only tool, no package import
    if cache:
        return Path(cache) / "flight"
    return Path.home() / ".cache" / "dynamo_tpu" / "flight"


def latest_flight_dump(directory: Path) -> Path | None:
    dumps = sorted(
        directory.glob("flight-*.jsonl"),
        key=lambda p: p.stat().st_mtime,
    )
    return dumps[-1] if dumps else None


def format_flight_record(rec: dict) -> str:
    """One human line per flight record: monotonic timestamp, kind, and the
    remaining fields as k=v in recorded order."""
    t = rec.get("t")
    head = f"{t:12.3f}" if isinstance(t, (int, float)) else f"{'-':>12}"
    kind = str(rec.get("kind", "?"))
    if kind == "event":
        kind = f"event:{rec.get('event', '?')}"
    body = " ".join(
        f"{k}={v}" for k, v in rec.items()
        if k not in ("t", "kind", "event", "schema_version")
    )
    return f"{head}  {kind:<22} {body}"


def tail_flight(
    directory: Path, follow: bool, interval: float, as_json: bool
) -> int:
    """Print the newest flight dump; with ``follow``, keep polling for a
    newer dump file and print its records as they land (``tail -F`` across
    dump generations)."""
    current: Path | None = None
    printed = 0
    while True:
        newest = latest_flight_dump(directory)
        if newest is None:
            if not follow:
                print(f"no flight dumps under {directory}")
                return 1
        else:
            if newest != current:
                current, printed = newest, 0
                if not as_json:
                    print(f"== {current}")
            lines = current.read_text().splitlines()
            for line in lines[printed:]:
                if not line.strip():
                    continue
                if as_json:
                    print(line)
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    print(line)
                    continue
                if "records" in rec and "kind" not in rec:
                    print(
                        f"# dump source={rec.get('source')} "
                        f"reason={rec.get('reason')} records={rec.get('records')} "
                        f"at={rec.get('dumped_at')}"
                    )
                else:
                    print(format_flight_record(rec))
            printed = len(lines)
        if not follow:
            return 0
        sys.stdout.flush()
        time.sleep(interval)


# -- rendering ---------------------------------------------------------------
def _pct(value: float | None) -> str:
    return "-" if value is None else f"{100.0 * value:5.1f}%"


def _num(value: float | None, width: int = 8) -> str:
    if value is None:
        return "-".rjust(width)
    if value >= 1e6:
        return f"{value / 1e6:.1f}M".rjust(width)
    if value >= 1e4:
        return f"{value / 1e3:.1f}k".rjust(width)
    return f"{value:.6g}".rjust(width)


def render_table(snap: dict) -> str:
    lines: list[str] = []
    ts = time.strftime("%H:%M:%S", time.localtime(snap.get("ts", time.time())))
    lines.append(f"dynamo-tpu fleet — {ts}")
    workers = snap.get("workers") or {}
    if snap.get("workers_error"):
        lines.append(f"  workers: unreachable ({snap['workers_error']})")
    if workers:
        lines.append(
            f"  {'WORKER':<10} {'SLICE/HOP':>10} {'MFU':>7} {'BW':>7} "
            f"{'GOODPUT/s':>10} "
            f"{'KV':>7} {'OCC':>7} {'RUN':>5} {'WAIT':>5} {'PREEMPT':>8} "
            f"{'WASTED':>8} {'PF-HIT':>7} {'UNI':>6} {'DRAIN':>6} "
            f"{'XFER-HID':>8} {'FLIGHT':>8}"
        )
        for wid in sorted(workers):
            r = workers[wid]
            placement = (
                f"{r.get('slice', '-')}/{r.get('hop', '-')}"
                if ("slice" in r or "hop" in r) else "-"
            )
            lines.append(
                f"  {wid:<10} {placement:>10} {_pct(r.get('mfu_perc')):>7} "
                f"{_pct(r.get('bandwidth_util_perc')):>7} "
                f"{_num(r.get('goodput_tokens_per_second'), 10)} "
                f"{_pct(r.get('kv_usage_perc')):>7} "
                f"{_pct(r.get('batch_occupancy_perc')):>7} "
                f"{_num(r.get('running'), 5)} {_num(r.get('waiting'), 5)} "
                f"{_num(r.get('preemptions'), 8)} {_num(r.get('wasted_tokens'), 8)} "
                f"{_pct(r.get('prefetch_hit_ratio')):>7} "
                f"{_num(r.get('unified_windows'), 6)} "
                f"{_num(r.get('admission_drains'), 6)} "
                f"{_pct(r.get('disagg_transfer_hidden_ratio') if r.get('disagg_remote_prefills') else None):>8} "
                f"{_num(r.get('flight_records'), 8)}"
            )
            if r.get("flight_dumps") or r.get("flight_dropped"):
                lines.append(
                    "  " + " " * 10 + " flight: "
                    f"dumps={r.get('flight_dumps', 0):g} "
                    f"last={r.get('flight_last_dump_reason', '-')} "
                    f"buf={_num(r.get('flight_buffer_bytes'), 1).strip()}B "
                    f"dropped={r.get('flight_dropped', 0):g}"
                )
            tiers = r.get("offload_tiers") or {}
            if tiers:
                cells = []
                for tier in sorted(tiers):
                    t = tiers[tier]
                    cell = f"{tier} {t.get('used', 0):g}/{t.get('blocks', 0):g}"
                    if t.get("pinned"):
                        cell += f" (pin {t['pinned']:g})"
                    cells.append(cell)
                hidden = r.get("prefetch_hidden_seconds")
                tail = (
                    f"   hidden {hidden:.2f}s" if hidden else ""
                )
                lines.append("  " + " " * 10 + " tiers: " + "  ".join(cells) + tail)
        fleet = snap.get("fleet") or {}
        if fleet:
            lines.append(
                f"  {'FLEET':<10} {'':>10} {_pct(fleet.get('mfu_perc_avg')):>7} {'':>7} "
                f"{_num(fleet.get('goodput_tokens_per_second'), 10)} "
                f"{_pct(fleet.get('kv_usage_perc_avg')):>7} {'':>7} "
                f"{_num(fleet.get('running'), 5)} {_num(fleet.get('waiting'), 5)}"
            )
    planner = snap.get("planner") or {}
    if planner:
        cells = []
        for pool in sorted(planner.get("pools") or {}):
            row = planner["pools"][pool]
            cell = f"{pool}={row.get('target_replicas', 0):g}"
            cap = row.get("observed_capacity_tok_s")
            if cap:
                cell += f" ({cap:.0f} tok/s/replica)"
            cells.append(cell)
        burn = planner.get("burn_rate_input")
        tail = f"   burn-in={burn:.2f}" if burn is not None else ""
        lines.append("  PLANNER    targets: " + "  ".join(cells) + tail)
    front = snap.get("frontend") or {}
    if front:
        lines.append("")
        if front.get("error"):
            lines.append(f"  frontend: unreachable ({front['error']})")
        else:
            lines.append(
                f"  frontend: inflight={front.get('inflight', 0):g} "
                f"requests={front.get('requests_total', 0):g} "
                f"shed={front.get('shed_total', 0):g} "
                f"mig={front.get('migrations_committed', 0):g}"
                + (
                    f" (aborted {front['migrations_aborted']:g})"
                    if front.get("migrations_aborted") else ""
                )
            )
        if front.get("slo_error"):
            lines.append(f"  slo: unreachable ({front['slo_error']})")
        slo = front.get("slo") or {}
        objectives = slo.get("objectives") or {}
        if objectives:
            windows = [str(int(w)) for w in slo.get("windows_s", [])]
            header = "  SLO burn   " + " ".join(f"{w + 's':>10}" for w in windows)
            lines.append(header)
            for name, obj in objectives.items():
                cells = []
                for w in windows:
                    rate = (obj.get("windows", {}).get(w) or {}).get("burn_rate", 0.0)
                    cells.append(f"{rate:>10.2f}")
                target = obj.get("target")
                lines.append(
                    f"  {name:<10} " + " ".join(cells) + f"   (target {target:g})"
                )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--frontend", help="frontend base URL (http://host:port)")
    parser.add_argument("--worker", help="metrics service base URL")
    parser.add_argument("--interval", type=float, default=2.0)
    parser.add_argument("--once", action="store_true", help="one frame, then exit")
    parser.add_argument("--json", action="store_true", help="emit JSON snapshots")
    parser.add_argument("--timeout", type=float, default=5.0)
    parser.add_argument("--flight", action="store_true",
                        help="tail the newest flight-recorder dump instead "
                             "of polling /metrics (local files, no URLs)")
    parser.add_argument("--flight-dir", default=None,
                        help="flight dump directory (default: DYN_FLIGHT_DIR "
                             "/ DYN_CACHE_DIR/flight / ~/.cache/dynamo_tpu/flight)")
    args = parser.parse_args(argv)
    if args.flight:
        return tail_flight(
            flight_dump_dir(args.flight_dir),
            follow=not args.once,
            interval=args.interval,
            as_json=args.json,
        )
    if not args.frontend and not args.worker:
        parser.error("give --frontend and/or --worker")

    while True:
        snap = collect_snapshot(args.frontend, args.worker, args.timeout)
        if args.json:
            print(json.dumps(snap))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear screen, home cursor
            print(render_table(snap))
        if args.once:
            # exit nonzero only when EVERY requested surface was
            # unreachable: a bench gating on --once must not mistake a
            # reachable-but-idle fleet (no workers registered yet, or /slo
            # alone down) for a dead one
            worker_up = args.worker and "workers_error" not in snap
            frontend_up = args.frontend and not snap["frontend"].get("error")
            return 0 if (worker_up or frontend_up) else 1
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
